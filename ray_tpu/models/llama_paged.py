"""Paged-KV inference path for the Llama family: chunked prefill +
block-table decode over a shared page pool.

Extends the static-slot design (models/llama_decode.py) the way vLLM's
PagedAttention extends dense slot caches on GPU — re-thought for TPU
static shapes:

- The cache is a POOL: ``[L, P, KVH, page, hd]`` (layers, num_pages,
  kv_heads, page_size, head_dim — (page, hd) minor so the Pallas
  kernel's page blocks satisfy TPU tiling).
  A sequence owns an ordered page list (its block table, host-side).
  HBM cost tracks ACTUAL tokens in flight, not slots × max_len, so one
  chip holds far longer contexts; identical prompt prefixes share pages
  (serve/paged_engine.py's prefix cache).
- Prefill is CHUNKED: the prompt runs through ``prefill_chunk`` in
  bucket-sized pieces, each attending to the pages written so far plus
  itself causally. Prompt length is bounded by max context, not by the
  prefill bucket; a long prompt never stalls the decode batch for more
  than one chunk.
- Decode gathers each slot's pages: the Pallas page-gather kernel
  (ops/paged_attention.py) on a bare TPU, the XLA gather path under
  GSPMD/tensor-parallel or on CPU. The in-flight token's K/V merges via
  an explicit self-term (exact online-softmax merge), and lands in the
  pool with one in-place scatter — the same HBM discipline as the dense
  decode_step.

All programs keep static shapes: block tables are [S, MAXP] with MAXP =
ceil(max_context / page_size); trailing entries are clamped/masked.
Reference analogue: the reference ships no paging at all (it serves via
torch); the public analogue is vLLM's PagedAttention, rebuilt TPU-first.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig
from ray_tpu.models.llama_decode import _mlp, _project_qkv, _w, sample_tokens
from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies

_NEG_INF = -1e30


def init_paged_cache(cfg: LlamaConfig, num_pages: int, page_size: int,
                     mesh=None) -> Dict[str, jax.Array]:
    """Pool layout [L, P, KVH, page, hd]: (page, hd) stay the minor dims
    so the Pallas kernel's page blocks satisfy TPU tiling (÷8, ÷128)."""
    hd = cfg.head_dim_
    shape = (cfg.num_layers, num_pages, cfg.num_kv_heads, page_size, hd)
    cache = {"k": jnp.zeros(shape, cfg.dtype),
             "v": jnp.zeros(shape, cfg.dtype)}
    if mesh is not None:
        cache = jax.device_put(cache, paged_cache_shardings(cfg, mesh))
    return cache


def paged_cache_shardings(cfg: LlamaConfig, mesh):
    """Page-pool shardings under tensor parallelism: the KV-head axis
    shards over ``tp`` (same rule as the dense cache — each chip owns
    its heads' pages); replicate when tp does not divide KVH."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    tp = dict(getattr(mesh, "shape", {})).get("tp", 1)
    if tp > 1 and cfg.num_kv_heads % tp == 0:
        sh = NamedSharding(mesh, P(None, None, "tp", None, None))
    else:
        sh = NamedSharding(mesh, P())
    return {"k": sh, "v": sh}


def prefill_chunk(cfg: LlamaConfig, params, cache: Dict[str, jax.Array],
                  tokens: jax.Array, block_table: jax.Array,
                  ctx0: jax.Array, n_valid: jax.Array
                  ) -> Tuple[Dict[str, jax.Array], jax.Array]:
    """One prompt chunk for ONE sequence: tokens [1, C] (padded), at
    global positions ctx0..ctx0+n_valid-1; block_table [MAXP] covers the
    pages allocated so far (history AND this chunk's span).

    Attends to the pages written by previous chunks (positions < ctx0)
    plus itself causally, writes its K/V into the pool (pad positions
    dropped), and returns (cache, logits [1, vocab] at the chunk's last
    valid token) — the final chunk's logits seed the first generated
    token.
    """
    C = tokens.shape[1]
    hd = cfg.head_dim_
    page = cache["k"].shape[3]
    num_pages = cache["k"].shape[1]
    MAXP = block_table.shape[0]
    T_hist = MAXP * page
    rep = cfg.num_heads // cfg.num_kv_heads

    x = params["embed"].astype(cfg.dtype)[tokens]          # [1, C, h]
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
    # rope table over the full context window; chunk rows use absolute
    # positions ctx0+i
    Tmax = T_hist
    cos, sin = rope_frequencies(hd, Tmax, cfg.rope_theta, dtype=cfg.dtype,
                                scaling=cfg.rope_scaling_dict)
    pos_c = ctx0 + jnp.arange(C, dtype=jnp.int32)          # [C]
    ci = jnp.arange(C, dtype=jnp.int32)

    # masks are position-only — shared across layers
    hist_mask = (jnp.arange(T_hist)[None] < ctx0)          # [1, T_hist]
    self_mask = ci[:, None] >= ci[None, :]                 # [C, C] causal
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))

    def layer(x, inp):
        p, kp, vp = inp                                    # pages [P,KVH,pg,hd]
        q, k, v, _ = _project_qkv(cfg, p, x)               # [1,C,H,hd]
        q = apply_rope(q, cos, sin, positions=pos_c[None])
        k = apply_rope(k, cos, sin, positions=pos_c[None])
        # [MAXP, KVH, page, hd] -> [KVH, T_hist, hd]
        ks = jnp.moveaxis(kp[block_table], 1, 0).reshape(
            cfg.num_kv_heads, T_hist, hd)
        vs = jnp.moveaxis(vp[block_table], 1, 0).reshape(
            cfg.num_kv_heads, T_hist, hd)
        q2 = q[0].reshape(C, cfg.num_kv_heads, rep, hd)
        s_hist = jnp.einsum("ckgd,ktd->ckgt", q2, ks,
                            preferred_element_type=jnp.float32) * scale
        s_hist = jnp.where(hist_mask[0][None, None, None], s_hist,
                           _NEG_INF)
        s_self = jnp.einsum("ckgd,ukd->ckgu", q2, k[0],
                            preferred_element_type=jnp.float32) * scale
        s_self = jnp.where(self_mask[:, None, None], s_self, _NEG_INF)
        scores = jnp.concatenate([s_hist, s_self], axis=-1)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        attn = (jnp.einsum("ckgt,ktd->ckgd", probs[..., :T_hist], vs)
                + jnp.einsum("ckgu,ukd->ckgd", probs[..., T_hist:], v[0]))
        attn = attn.reshape(1, C, cfg.num_heads * hd)
        x = x + jnp.dot(attn, _w(p, "wo", cfg.dtype),
                        preferred_element_type=jnp.float32).astype(cfg.dtype)
        x = x + _mlp(cfg, p, x)
        return x, (k[0], v[0])                             # [C, KVH, hd]

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"]))
    # one scatter of the whole chunk into the pool: position ctx0+i goes
    # to page block_table[(ctx0+i)//page] at offset (ctx0+i)%page; pad
    # rows (i >= n_valid) redirect out of bounds and drop. Non-adjacent
    # advanced indices (dims 1 and 3) put the index dim FIRST in the
    # update: [C, L, KVH, hd].
    pidx = block_table[jnp.clip(pos_c // page, 0, MAXP - 1)]
    pidx = jnp.where(ci < n_valid, pidx, num_pages)
    poff = pos_c % page
    upd_k = jnp.moveaxis(new_k, 1, 0)                      # [C, L, KVH, hd]
    upd_v = jnp.moveaxis(new_v, 1, 0)
    ck = cache["k"].at[:, pidx, :, poff].set(upd_k, mode="drop",
                                             unique_indices=True)
    cv = cache["v"].at[:, pidx, :, poff].set(upd_v, mode="drop",
                                             unique_indices=True)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    x_last = x[0, jnp.maximum(n_valid - 1, 0)]             # [h]
    head = (params["embed"].astype(cfg.dtype).T if cfg.tie_embeddings
            else _w(params, "lm_head", cfg.dtype))
    logits = jnp.dot(x_last[None], head,
                     preferred_element_type=jnp.float32)   # [1, vocab]
    return {"k": ck, "v": cv}, logits


def paged_decode_step(cfg: LlamaConfig, params, cache: Dict[str, jax.Array],
                      tokens: jax.Array, positions: jax.Array,
                      active: jax.Array, block_table: jax.Array,
                      use_kernel: bool = False, interpret: bool = False
                      ) -> Tuple[Dict[str, jax.Array], jax.Array]:
    """One token for every slot over paged KV.

    tokens/positions/active [S] as dense decode_step; block_table
    [S, MAXP] int32. History attention streams pages (Pallas kernel when
    ``use_kernel``); the in-flight token merges via an exact
    online-softmax self-term; new K/V lands in one in-place scatter.
    """
    from ray_tpu.ops.paged_attention import (paged_attention,
                                             paged_attention_reference)

    S = tokens.shape[0]
    page = cache["k"].shape[3]
    num_pages = cache["k"].shape[1]
    MAXP = block_table.shape[1]
    hd = cfg.head_dim_
    rep = cfg.num_heads // cfg.num_kv_heads
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))

    x = params["embed"].astype(cfg.dtype)[tokens][:, None]  # [S, 1, h]
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
    cos, sin = rope_frequencies(hd, MAXP * page, cfg.rope_theta,
                                dtype=cfg.dtype,
                                scaling=cfg.rope_scaling_dict)
    pos2 = positions[:, None]

    def layer(carry, inp):
        x = carry
        p, kp, vp = inp
        q, k, v, _ = _project_qkv(cfg, p, x)
        q = apply_rope(q, cos, sin, positions=pos2)
        k = apply_rope(k, cos, sin, positions=pos2)
        k1, v1 = k[:, 0], v[:, 0]                          # [S, KVH, hd]
        q2 = q[:, 0].reshape(S, cfg.num_kv_heads, rep, hd)
        if use_kernel:
            acc, m, l = paged_attention(q2, kp, vp, block_table,
                                        positions, interpret=interpret)
        else:
            acc, m, l = paged_attention_reference(q2, kp, vp, block_table,
                                                  positions)
        # exact merge of the in-flight token's self term into the
        # flash-style (acc, m, l) triple
        s_self = jnp.einsum("skgd,skd->skg", q2, k1,
                            preferred_element_type=jnp.float32) * scale
        m_tot = jnp.maximum(m, s_self)
        alpha = jnp.exp(m - m_tot)
        p_self = jnp.exp(s_self - m_tot)
        num = (acc * alpha[..., None]
               + p_self[..., None] * v1[:, :, None, :].astype(jnp.float32))
        den = l * alpha + p_self
        attn = (num / jnp.maximum(den, 1e-30)[..., None]).astype(cfg.dtype)
        attn = attn.reshape(S, 1, cfg.num_heads * hd)
        x = x + jnp.dot(attn, _w(p, "wo", cfg.dtype),
                        preferred_element_type=jnp.float32).astype(cfg.dtype)
        x = x + _mlp(cfg, p, x)
        return x, (k1, v1)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"]))
    pidx = jnp.take_along_axis(
        block_table, jnp.clip(positions // page, 0, MAXP - 1)[:, None],
        axis=1)[:, 0]
    pidx = jnp.where(active, pidx, num_pages)              # drop inactive
    poff = positions % page
    # non-adjacent advanced indices (dims 1, 3): update is [S, L, KVH, hd]
    ck = cache["k"].at[:, pidx, :, poff].set(
        jnp.moveaxis(new_k, 1, 0), mode="drop")
    cv = cache["v"].at[:, pidx, :, poff].set(
        jnp.moveaxis(new_v, 1, 0), mode="drop")
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = (params["embed"].astype(cfg.dtype).T if cfg.tie_embeddings
            else _w(params, "lm_head", cfg.dtype))
    logits = jnp.dot(x[:, 0], head, preferred_element_type=jnp.float32)
    return {"k": ck, "v": cv}, logits


def paged_decode_chunk(cfg: LlamaConfig, params,
                       cache: Dict[str, jax.Array], tokens: jax.Array,
                       positions: jax.Array, active: jax.Array,
                       block_table: jax.Array, num_steps: int,
                       rng: Optional[jax.Array] = None,
                       temperature: Optional[jax.Array] = None,
                       top_k: int = 0, sample: bool = True,
                       use_kernel: bool = False, interpret: bool = False
                       ) -> Tuple[Dict[str, jax.Array], jax.Array,
                                  jax.Array, jax.Array]:
    """``num_steps`` paged decode steps in one program, chaining tokens
    on device exactly like the dense decode_chunk (same return contract:
    cache, out [k, S], next_tokens [S], next_positions [S]). The block
    table must already cover positions+num_steps tokens per active slot
    (the engine's allocator grows tables before dispatch)."""
    S = tokens.shape[0]
    if temperature is None:
        temperature = jnp.zeros((S,), jnp.float32)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def step(carry, _):
        cache, toks, pos, key = carry
        cache, logits = paged_decode_step(
            cfg, params, cache, toks, pos, active, block_table,
            use_kernel=use_kernel, interpret=interpret)
        if sample:
            key, sub = jax.random.split(key)
            nxt = sample_tokens(logits, sub, temperature, top_k)
        else:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(active, nxt, toks)
        return (cache, nxt, pos + active.astype(jnp.int32), key), nxt

    (cache, nxt, pos, _), out = jax.lax.scan(
        step, (cache, tokens, positions, rng), None, length=num_steps)
    return cache, out, nxt, pos


def make_paged_engine_fns(cfg: LlamaConfig, params, mesh=None,
                          use_kernel: Optional[bool] = None):
    """Jitted paged-engine programs (params as jit ARGUMENTS — a closure
    would bake the weights into the HLO as literals; see
    llama_decode.make_engine_fns). Pool geometry (num_pages, page_size,
    slot count) lives in the cache/block-table ARRAYS the returned
    programs take, not here — the jitted programs specialize on those
    shapes at first call.

    use_kernel: None → Pallas page-gather on a bare TPU, XLA gather under
    a mesh (GSPMD cannot shard a Pallas call) or off-TPU.
    """
    if use_kernel is None:
        use_kernel = jax.default_backend() == "tpu" and mesh is None
    if mesh is not None:
        from ray_tpu.models import llama as _llama

        params = jax.device_put(params, _llama.param_shardings(cfg, mesh))
    prefill_j = jax.jit(prefill_chunk, static_argnums=(0,),
                        donate_argnums=(2,))
    chunk_j = jax.jit(paged_decode_chunk,
                      static_argnums=(0, 7, 10, 11, 12, 13),
                      donate_argnums=(2,))

    def pre(cache, tokens, block_table, ctx0, n_valid):
        return prefill_j(cfg, params, cache, tokens, block_table, ctx0,
                         n_valid)

    def dec_chunk(cache, tokens, positions, active, block_table,
                  num_steps, rng=None, temperature=None, top_k=0,
                  sample=True):
        return chunk_j(cfg, params, cache, tokens, positions, active,
                       block_table, num_steps, rng, temperature, top_k,
                       sample, use_kernel, False)

    return pre, dec_chunk
