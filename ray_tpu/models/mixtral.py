"""Mixtral-style sparse MoE transformer, TPU-first.

BASELINE.json config #3 names "Mixtral 8x7B MoE, expert-parallel" — the
reference delegates the model to torch; this is the JAX-native design:

- Llama backbone (same attention stack, rms_norm/rope/GQA) with the dense
  MLP replaced by a top-k routed mixture of SwiGLU experts.
- GShard/Switch-style STATIC-capacity dispatch: routing builds dense
  dispatch/combine tensors and experts run as one grouped einsum over
  ``[experts, capacity, hidden]`` — every shape static, so the whole MoE
  layer is two einsums + the expert FFN on the MXU, and sharding the
  expert dim over the mesh's ``ep`` axis makes XLA insert the
  all-to-alls (tokens -> expert shards -> back) over ICI. No scatter,
  no sort, no dynamic shapes.
- Switch load-balancing auxiliary loss keeps routing uniform.

Parity oracle: with num_experts=1, top_k=1 and enough capacity the MoE
layer reduces exactly to the dense SwiGLU MLP (tested).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models import llama
from ray_tpu.ops.layers import rms_norm, rope_frequencies


@dataclass(frozen=True)
class MixtralConfig(llama.LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01

    @classmethod
    def mixtral_8x7b(cls, **kw) -> "MixtralConfig":
        cfg = cls(hidden_size=4096, intermediate_size=14336, num_layers=32,
                  num_heads=32, num_kv_heads=8, vocab_size=32000,
                  num_experts=8, top_k=2)
        return replace(cfg, **kw)

    @classmethod
    def moe_proxy(cls, **kw) -> "MixtralConfig":
        """~MoE analogue of the 1b llama proxy (for single-chip benches)."""
        cfg = cls(hidden_size=1024, intermediate_size=2816, num_layers=8,
                  num_heads=8, num_kv_heads=4, vocab_size=32000,
                  num_experts=8, top_k=2)
        return replace(cfg, **kw)

    @classmethod
    def tiny(cls, **kw) -> "MixtralConfig":
        cfg = cls(vocab_size=256, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2,
                  max_seq_len=128, dtype=jnp.float32, remat=False,
                  num_experts=4, top_k=2)
        return replace(cfg, **kw)


def logical_axes(cfg: MixtralConfig) -> Dict[str, Any]:
    """Parameter logical axes; expert dims map to the ep mesh axis."""
    base = llama.logical_axes(cfg)
    L = ("layer",)
    base["layers"].pop("w_gate")
    base["layers"].pop("w_up")
    base["layers"].pop("w_down")
    base["layers"].update({
        "router": L + ("embed", "expert"),
        "e_gate": L + ("expert", "embed", "mlp"),
        "e_up": L + ("expert", "embed", "mlp"),
        "e_down": L + ("expert", "mlp", "embed"),
    })
    return base


def logical_axes_without_layer(cfg: MixtralConfig):
    return jax.tree_util.tree_map(
        lambda t: tuple(None if a == "layer" else a for a in t),
        logical_axes(cfg), is_leaf=lambda x: isinstance(x, tuple))


def init_params(cfg: MixtralConfig, key: jax.Array) -> Dict[str, Any]:
    params = llama.init_params(cfg, key)
    h, ffn, L, E = (cfg.hidden_size, cfg.intermediate_size,
                    cfg.num_layers, cfg.num_experts)
    for name in ("w_gate", "w_up", "w_down"):
        params["layers"].pop(name)
    keys = jax.random.split(jax.random.fold_in(key, 7), 4)

    def norm_init(k, shape, fan_in):
        return (jax.random.truncated_normal(k, -3, 3, shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(cfg.param_dtype)

    params["layers"].update({
        "router": norm_init(keys[0], (L, h, E), h),
        "e_gate": norm_init(keys[1], (L, E, h, ffn), h),
        "e_up": norm_init(keys[2], (L, E, h, ffn), h),
        "e_down": norm_init(keys[3], (L, E, ffn, h), ffn),
    })
    return params


def _capacity(cfg: MixtralConfig, num_tokens: int) -> int:
    cap = int(math.ceil(cfg.capacity_factor * num_tokens * cfg.top_k
                        / cfg.num_experts))
    return max(8, ((cap + 7) // 8) * 8)  # MXU-friendly multiple of 8


def moe_layer(cfg: MixtralConfig, p, x: jax.Array
              ) -> Tuple[jax.Array, jax.Array]:
    """Routed expert MLP. x: [b, s, h] -> (out [b, s, h], aux_loss)."""
    b, s, h = x.shape
    n = b * s
    E, K = cfg.num_experts, cfg.top_k
    C = _capacity(cfg, n)
    xt = x.reshape(n, h)

    logits = jnp.dot(xt, p["router"].astype(cfg.dtype),
                     preferred_element_type=jnp.float32)   # [n, E] fp32
    probs = jax.nn.softmax(logits, axis=-1)

    # top-k selection; renormalized gate weights (Mixtral convention)
    top_w, top_e = jax.lax.top_k(probs, K)                 # [n, K]
    top_w = top_w / jnp.maximum(top_w.sum(-1, keepdims=True), 1e-9)

    # Switch aux loss: fraction of tokens routed * mean router prob per
    # expert (computed on the top-1 assignment)
    me = probs.mean(axis=0)                                # [n->E] mean prob
    ce = jnp.zeros((E,), jnp.float32).at[top_e[:, 0]].add(1.0) / n
    aux = cfg.router_aux_coef * E * jnp.sum(me * ce)

    # static-capacity position assignment: for expert e, tokens keep their
    # routing in arrival order until capacity; overflow drops (standard)
    onehot = jax.nn.one_hot(top_e, E, dtype=jnp.int32)     # [n, K, E]
    flat = onehot.reshape(n * K, E)
    pos = jnp.cumsum(flat, axis=0) - 1                     # [n*K, E]
    pos = (pos * flat).sum(-1).reshape(n, K)               # slot per (tok,k)
    expert_of = top_e                                      # [n, K]
    keep = (pos < C)

    # dispatch one-hots: [n, K, C] scatter into each expert's buffer
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, C), C + 1,
                            dtype=cfg.dtype)[..., :C]      # drops overflow
    disp = jnp.einsum("nke,nkc->nec", onehot.astype(cfg.dtype), pos_oh)
    comb = jnp.einsum("nke,nkc,nk->nec", onehot.astype(jnp.float32),
                      pos_oh.astype(jnp.float32), top_w).astype(cfg.dtype)

    # tokens -> expert buffers [E, C, h]; with "expert" sharded over ep
    # this einsum is the all-to-all
    ex_in = jnp.einsum("nec,nh->ech", disp, xt)
    # grouped expert SwiGLU
    g = jnp.einsum("ech,ehf->ecf", ex_in, p["e_gate"].astype(cfg.dtype),
                   preferred_element_type=jnp.float32)
    u = jnp.einsum("ech,ehf->ecf", ex_in, p["e_up"].astype(cfg.dtype),
                   preferred_element_type=jnp.float32)
    act = (jax.nn.silu(g) * u).astype(cfg.dtype)
    ex_out = jnp.einsum("ecf,efh->ech", act, p["e_down"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32).astype(cfg.dtype)
    # back to tokens, weighted by gates (the reverse all-to-all)
    out = jnp.einsum("nec,ech->nh", comb, ex_out)
    return out.reshape(b, s, h), aux


def _layer(cfg: MixtralConfig, x, p, cos, sin, mesh=None):
    """One decoder block: shared llama attention + MoE MLP."""
    x = llama.attention_block(cfg, x, p, cos, sin, mesh=mesh)
    h2 = rms_norm(x, p["mlp_norm"], cfg.rms_norm_eps)
    moe_out, aux = moe_layer(cfg, p, h2)
    return x + moe_out, aux


def forward(cfg: MixtralConfig, params, tokens: jax.Array, mesh=None
            ) -> Tuple[jax.Array, jax.Array]:
    """tokens [b, s] -> (logits [b, s, vocab] fp32, aux_loss scalar)."""
    x = params["embed"].astype(cfg.dtype)[tokens]
    cos, sin = rope_frequencies(cfg.head_dim_, tokens.shape[1],
                                cfg.rope_theta, dtype=cfg.dtype,
                                scaling=cfg.rope_scaling_dict)

    if cfg.remat_policy != "full" or not cfg.scan_layers:
        raise ValueError(
            "remat_policy/scan_layers are dense-Llama knobs; the MoE "
            "forward always scans under full remat — drop them rather "
            "than read tuning signal from a no-op")
    layer_fn = lambda x_, p_: _layer(cfg, x_, p_, cos, sin, mesh=mesh)
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)

    def scan_body(x_, p_):
        x2, aux = layer_fn(x_, p_)
        return x2, aux

    x, auxes = jax.lax.scan(scan_body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    logits = jnp.dot(x, head.astype(cfg.dtype),
                     preferred_element_type=jnp.float32)
    return logits, auxes.sum()


def loss_fn(cfg: MixtralConfig, params, batch: Dict[str, jax.Array],
            mesh=None) -> jax.Array:
    tokens = batch["tokens"]
    logits, aux = forward(cfg, params, tokens[:, :-1], mesh=mesh)
    mask = batch.get("mask")
    if mask is not None:
        mask = mask[:, 1:]
    return llama.cross_entropy_loss(logits, tokens[:, 1:], mask) + aux


def param_shardings(cfg: MixtralConfig, mesh):
    from ray_tpu.parallel.sharding import shard_pytree_like

    return shard_pytree_like(logical_axes_without_layer(cfg), mesh)
