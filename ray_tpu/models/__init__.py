"""Model zoo: TPU-first implementations with logical-axis shardings."""

from ray_tpu.models import gpt2, llama  # noqa: F401
