"""GPT-2 family (decoder-only, learned positions, LayerNorm, GELU MLP).

Covers the reference north-star config "GPT-2-125M on wikitext-2"
(BASELINE.json configs[0]). Same TPU-first structure as llama.py: stacked
layers + lax.scan, logical axis names, bf16/fp32 mix, optional remat.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import attention_reference, flash_attention


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50_257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    ln_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attn_impl: str = "auto"
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def gpt2_125m(cls, **kw) -> "GPT2Config":
        return replace(cls(), **kw)

    @classmethod
    def tiny(cls, **kw) -> "GPT2Config":
        return replace(
            cls(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=128, dtype=jnp.float32, remat=False), **kw)


def logical_axes(cfg: GPT2Config) -> Dict[str, Any]:
    L = ("layer",)
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "layers": {
            "ln1_g": L + ("embed",), "ln1_b": L + ("embed",),
            "w_qkv": L + ("embed", "qkv"), "b_qkv": L + ("qkv",),
            "w_proj": L + ("qkv", "embed"), "b_proj": L + ("embed",),
            "ln2_g": L + ("embed",), "ln2_b": L + ("embed",),
            "w_fc": L + ("embed", "mlp"), "b_fc": L + ("mlp",),
            "w_out": L + ("mlp", "embed"), "b_out": L + ("embed",),
        },
        "lnf_g": ("embed",), "lnf_b": ("embed",),
    }


def logical_axes_without_layer(cfg: GPT2Config):
    return jax.tree_util.tree_map(
        lambda t: tuple(None if a == "layer" else a for a in t),
        logical_axes(cfg), is_leaf=lambda x: isinstance(x, tuple))


def init_params(cfg: GPT2Config, key: jax.Array) -> Dict[str, Any]:
    h, L = cfg.hidden_size, cfg.num_layers
    keys = jax.random.split(key, 6)

    def ninit(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(
            cfg.param_dtype)

    return {
        "wte": ninit(keys[0], (cfg.vocab_size, h)),
        "wpe": ninit(keys[1], (cfg.max_seq_len, h), 0.01),
        "layers": {
            "ln1_g": jnp.ones((L, h), cfg.param_dtype),
            "ln1_b": jnp.zeros((L, h), cfg.param_dtype),
            "w_qkv": ninit(keys[2], (L, h, 3 * h)),
            "b_qkv": jnp.zeros((L, 3 * h), cfg.param_dtype),
            "w_proj": ninit(keys[3], (L, h, h), 0.02 / math.sqrt(2 * L)),
            "b_proj": jnp.zeros((L, h), cfg.param_dtype),
            "ln2_g": jnp.ones((L, h), cfg.param_dtype),
            "ln2_b": jnp.zeros((L, h), cfg.param_dtype),
            "w_fc": ninit(keys[4], (L, h, 4 * h)),
            "b_fc": jnp.zeros((L, 4 * h), cfg.param_dtype),
            "w_out": ninit(keys[5], (L, 4 * h, h), 0.02 / math.sqrt(2 * L)),
            "b_out": jnp.zeros((L, h), cfg.param_dtype),
        },
        "lnf_g": jnp.ones((h,), cfg.param_dtype),
        "lnf_b": jnp.zeros((h,), cfg.param_dtype),
    }


def _layer_norm(x, g, b, eps):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * g.astype(jnp.float32)
            + b.astype(jnp.float32)).astype(x.dtype)


def _attend(cfg: GPT2Config, q, k, v):
    impl = cfg.attn_impl
    if impl == "auto":
        impl = "flash" if jax.default_backend() == "tpu" else "reference"
    if impl == "flash":
        return flash_attention(q, k, v, causal=True)
    return attention_reference(q, k, v, causal=True)


def _layer(cfg: GPT2Config, x, p):
    b, s, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim

    h1 = _layer_norm(x, p["ln1_g"], p["ln1_b"], cfg.ln_eps)
    qkv = (jnp.dot(h1, p["w_qkv"].astype(cfg.dtype),
                   preferred_element_type=jnp.float32)
           + p["b_qkv"].astype(jnp.float32)).astype(cfg.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, nh, hd)
    k = k.reshape(b, s, nh, hd)
    v = v.reshape(b, s, nh, hd)
    attn = _attend(cfg, q, k, v).reshape(b, s, h)
    proj = (jnp.dot(attn, p["w_proj"].astype(cfg.dtype),
                    preferred_element_type=jnp.float32)
            + p["b_proj"].astype(jnp.float32)).astype(cfg.dtype)
    x = x + proj

    h2 = _layer_norm(x, p["ln2_g"], p["ln2_b"], cfg.ln_eps)
    fc = (jnp.dot(h2, p["w_fc"].astype(cfg.dtype),
                  preferred_element_type=jnp.float32)
          + p["b_fc"].astype(jnp.float32))
    act = jax.nn.gelu(fc).astype(cfg.dtype)
    out = (jnp.dot(act, p["w_out"].astype(cfg.dtype),
                   preferred_element_type=jnp.float32)
           + p["b_out"].astype(jnp.float32)).astype(cfg.dtype)
    return x + out


def forward(cfg: GPT2Config, params, tokens: jax.Array) -> jax.Array:
    """tokens [b, s] → logits [b, s, vocab] (tied embeddings, as GPT-2)."""
    b, s = tokens.shape
    x = (params["wte"].astype(cfg.dtype)[tokens]
         + params["wpe"].astype(cfg.dtype)[:s][None])

    layer_fn = lambda x_, p_: _layer(cfg, x_, p_)
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)

    x, _ = jax.lax.scan(lambda x_, p_: (layer_fn(x_, p_), None),
                        x, params["layers"])
    x = _layer_norm(x, params["lnf_g"], params["lnf_b"], cfg.ln_eps)
    return jnp.dot(x, params["wte"].T.astype(cfg.dtype),
                   preferred_element_type=jnp.float32)


def loss_fn(cfg: GPT2Config, params, batch) -> jax.Array:
    from ray_tpu.models.llama import cross_entropy_loss

    tokens = batch["tokens"]
    logits = forward(cfg, params, tokens[:, :-1])
    mask = batch.get("mask")
    if mask is not None:
        mask = mask[:, 1:]
    return cross_entropy_loss(logits, tokens[:, 1:], mask)


def param_shardings(cfg: GPT2Config, mesh):
    from ray_tpu.parallel.sharding import shard_pytree_like

    return shard_pytree_like(logical_axes_without_layer(cfg), mesh)
