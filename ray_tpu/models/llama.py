"""Llama-3-style decoder-only transformer, TPU-first.

Design (none of this exists in the reference — it delegates models to
torch; this is the flagship model the north-star configs name):

- plain-jax pytree params with *stacked* layers and a ``lax.scan`` over the
  stack: one layer traced/compiled once regardless of depth.
- every parameter carries logical axis names (parallel/sharding.py) so the
  same model runs dp/fsdp/tp/sp by choosing a mesh; no model code changes.
- bf16 params/activations with fp32 accumulations (preferred_element_type)
  — MXU-native.
- ``jax.checkpoint`` around each layer (rematerialization: HBM traded for
  FLOPs on the backward pass).
- attention backend switch: "flash" (Pallas), "reference" (XLA), "ring"
  (sequence-parallel over the sp axis, KV blocks rotating on the ICI
  ring), "ulysses" (sequence-parallel via all-to-all head re-sharding).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import attention_reference, flash_attention
from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies, swiglu
from ray_tpu.ops.ring_attention import ring_attention


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    hidden_size: int = 4096
    intermediate_size: int = 14_336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: Optional[int] = None
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    rms_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attn_impl: str = "auto"  # auto | flash | reference | ring | ulysses
    # Qwen2-style additive q/k/v projection biases (the ONLY
    # architectural delta between Qwen2 and Llama at this level)
    attn_qkv_bias: bool = False
    # Gemma deltas: GeGLU gate activation ("gelu_tanh"), and embeddings
    # scaled by sqrt(hidden) at lookup. Gemma's (1+w) RMSNorm needs no
    # knob — the +1 folds into the stored norm weights at load time.
    mlp_act: str = "silu"  # silu | gelu_tanh
    embed_scale: float = 1.0
    # serving prefill attention: None = auto (Pallas flash on single-
    # chip TPU, fp32 reference elsewhere). The engine forces False under
    # tensor parallelism — a pallas_call inside a GSPMD-sharded jit
    # cannot be auto-partitioned like plain XLA ops.
    prefill_flash: Optional[bool] = None
    remat: bool = True
    # partial remat: this many TRAILING layers store activations instead
    # of recomputing (HBM for FLOPs; 0 = classic full per-layer remat).
    # Caveats: the head/tail split slices the stacked layer params, which
    # XLA may materialize as a duplicate of the stack — budget for it;
    # measured neutral-to-NEGATIVE on v5e-lite at 1B (BENCH_NOTES.md),
    # aimed at HBM-rich parts; sequential forward only (pp raises).
    remat_store_layers: int = 0
    # remat selectivity: "full" recomputes the whole layer on backward;
    # "save_qkv" keeps the post-rope q/k/v projections (HBM cost
    # b*s*(H+2*KVH)*hd*2 per layer ≈ 2.1 GB at the 1B bench shape) so
    # the backward skips their recompute — measured 806→782 ms at 1B on
    # v5e with bf16 adam momentum funding the HBM.
    remat_policy: str = "full"  # full | save_qkv
    # False = python-unrolled layer loop instead of lax.scan. The scan
    # carries the stacked weight GRADIENTS through its backward as
    # dynamic-update-slice'd buffers, which XLA partially re-copies per
    # iteration; unrolling removes that and measured +3% step throughput
    # at 1B on v5e (855→806 ms with the bf16-MLP fix, BENCH_NOTES r5).
    # Cost: compile time grows with depth (~30 s at 16 layers) — the
    # right trade for long training runs, wrong for tests/CI, so scan
    # stays the default.
    scan_layers: bool = True
    tie_embeddings: bool = False
    # optional llama3-style long-context rope scaling (the HF
    # rope_scaling dict; see ops/layers.rope_frequencies)
    rope_scaling: Optional[tuple] = None  # dict items, hashable for jit

    def __post_init__(self):
        # validate eagerly (not just when remat kicks in) so a typo'd
        # policy on a remat=False config cannot sit unnoticed until a
        # later remat=True run crashes at trace time
        if self.remat_policy not in ("full", "save_qkv"):
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r} "
                "(full | save_qkv)")

    @property
    def rope_scaling_dict(self):
        return dict(self.rope_scaling) if self.rope_scaling else None

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    # ---- presets -----------------------------------------------------------

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(**kw)

    @classmethod
    def llama3_1b_proxy(cls, **kw) -> "LlamaConfig":
        cfg = cls(hidden_size=2048, intermediate_size=5504, num_layers=16,
                  num_heads=16, num_kv_heads=8, vocab_size=32_000)
        return replace(cfg, **kw)

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        cfg = cls(vocab_size=256, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
                  dtype=jnp.float32, remat=False)
        return replace(cfg, **kw)


# Logical axis names for every parameter (rules in parallel/sharding.py map
# them onto the mesh; the leading "layer" dim of stacked params is unsharded
# until pipeline parallelism assigns it to "pp").
def logical_axes(cfg: LlamaConfig) -> Dict[str, Any]:
    L = ("layer",)
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": L + ("embed",),
            "wq": L + ("embed", "qkv"),
            "wk": L + ("embed", "qkv"),
            "wv": L + ("embed", "qkv"),
            "wo": L + ("qkv", "embed"),
            "mlp_norm": L + ("embed",),
            "w_gate": L + ("embed", "mlp"),
            "w_up": L + ("embed", "mlp"),
            "w_down": L + ("mlp", "embed"),
            # qkv biases shard with their projections' column split
            **({"bq": L + ("qkv",), "bk": L + ("qkv",),
                "bv": L + ("qkv",)} if cfg.attn_qkv_bias else {}),
        },
        "final_norm": ("embed",),
        # tied embeddings reuse params["embed"]; no separate lm_head leaf
        **({} if cfg.tie_embeddings else {"lm_head": ("embed", "vocab")}),
    }


def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Truncated-normal init (fan-in scaled), params in cfg.param_dtype."""
    h, ffn, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    hd = cfg.head_dim_
    qd = cfg.num_heads * hd
    kvd = cfg.num_kv_heads * hd
    keys = jax.random.split(key, 8)

    def norm_init(k, shape, fan_in):
        return (jax.random.truncated_normal(k, -3, 3, shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(cfg.param_dtype)

    params = {
        "embed": norm_init(keys[0], (cfg.vocab_size, h), h),
        "layers": {
            "attn_norm": jnp.ones((L, h), cfg.param_dtype),
            "wq": norm_init(keys[1], (L, h, qd), h),
            "wk": norm_init(keys[2], (L, h, kvd), h),
            "wv": norm_init(keys[3], (L, h, kvd), h),
            "wo": norm_init(keys[4], (L, qd, h), qd),
            "mlp_norm": jnp.ones((L, h), cfg.param_dtype),
            "w_gate": norm_init(keys[5], (L, h, ffn), h),
            "w_up": norm_init(keys[6], (L, h, ffn), h),
            "w_down": norm_init(keys[7], (L, ffn, h), ffn),
        },
        "final_norm": jnp.ones((h,), cfg.param_dtype),
    }
    if cfg.attn_qkv_bias:
        params["layers"]["bq"] = jnp.zeros((L, qd), cfg.param_dtype)
        params["layers"]["bk"] = jnp.zeros((L, kvd), cfg.param_dtype)
        params["layers"]["bv"] = jnp.zeros((L, kvd), cfg.param_dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = norm_init(
            jax.random.fold_in(key, 99), (h, cfg.vocab_size), h)
    return params


def _attend(cfg: LlamaConfig, q, k, v, mesh=None, seq_axis=None):
    impl = cfg.attn_impl
    if impl == "auto":
        impl = "flash" if jax.default_backend() == "tpu" else "reference"
    if impl == "flash":
        return flash_attention(q, k, v, causal=True)
    if impl in ("ring", "ulysses"):
        if seq_axis is not None:
            # already INSIDE a shard_map that includes the sp axis (the
            # pp pipeline program): run the per-shard body directly
            if impl == "ring":
                from ray_tpu.ops.ring_attention import ring_attention_local

                return ring_attention_local(q, k, v, seq_axis, causal=True)
            from ray_tpu.ops.ulysses import ulysses_attention_local

            return ulysses_attention_local(q, k, v, seq_axis, causal=True)
        if mesh is None:
            raise ValueError(
                f"attn_impl={impl!r} requires a mesh with an 'sp' axis")
        if impl == "ring":
            return ring_attention(q, k, v, mesh, axis_name="sp", causal=True)
        from ray_tpu.ops.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, mesh, axis_name="sp", causal=True)
    return attention_reference(q, k, v, causal=True)


def attention_block(cfg: LlamaConfig, x, p, cos, sin, mesh=None,
                    seq_axis=None):
    """Pre-norm attention sub-block with residual: x + wo(attend(qkv)).
    Shared by every model in the family (llama dense, mixtral MoE)."""
    b, s, _ = x.shape
    hd = cfg.head_dim_
    h1 = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
    q = jnp.dot(h1, p["wq"].astype(cfg.dtype),
                preferred_element_type=jnp.float32).astype(cfg.dtype)
    k = jnp.dot(h1, p["wk"].astype(cfg.dtype),
                preferred_element_type=jnp.float32).astype(cfg.dtype)
    v = jnp.dot(h1, p["wv"].astype(cfg.dtype),
                preferred_element_type=jnp.float32).astype(cfg.dtype)
    if "bq" in p:  # Qwen2-style qkv biases (structure is trace-static)
        q = q + p["bq"].astype(cfg.dtype)
        k = k + p["bk"].astype(cfg.dtype)
        v = v + p["bv"].astype(cfg.dtype)
    q = q.reshape(b, s, cfg.num_heads, hd)
    k = k.reshape(b, s, cfg.num_kv_heads, hd)
    v = v.reshape(b, s, cfg.num_kv_heads, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    # named for remat_policy="save_qkv" (no-ops otherwise): saving the
    # post-rope projections lets the backward skip the qkv matmul+rope
    # recompute — measured +4% step throughput at 1B for ~2.1 GB HBM
    from jax.ad_checkpoint import checkpoint_name

    q = checkpoint_name(q, "q_rope")
    k = checkpoint_name(k, "k_rope")
    v = checkpoint_name(v, "v_proj")
    attn = _attend(cfg, q, k, v, mesh=mesh, seq_axis=seq_axis)
    attn = attn.reshape(b, s, cfg.num_heads * hd)
    attn_out = jnp.dot(attn, p["wo"].astype(cfg.dtype),
                       preferred_element_type=jnp.float32).astype(cfg.dtype)
    return x + attn_out


def _layer(cfg: LlamaConfig, x, layer_params, cos, sin, mesh=None,
           seq_axis=None):
    """One decoder block. x: [b, s, h]."""
    p = layer_params
    x = attention_block(cfg, x, p, cos, sin, mesh=mesh,
                        seq_axis=seq_axis)
    h2 = rms_norm(x, p["mlp_norm"], cfg.rms_norm_eps)
    mlp = swiglu(h2, p["w_gate"].astype(cfg.dtype),
                 p["w_up"].astype(cfg.dtype), p["w_down"].astype(cfg.dtype),
                 act=cfg.mlp_act)
    return x + mlp


def forward(cfg: LlamaConfig, params: Dict[str, Any], tokens: jax.Array,
            mesh=None) -> jax.Array:
    """tokens [b, s] int32 → logits [b, s, vocab] float32."""
    x = params["embed"].astype(cfg.dtype)[tokens]
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
    cos, sin = rope_frequencies(cfg.head_dim_, tokens.shape[1],
                                cfg.rope_theta, dtype=cfg.dtype,
                                scaling=cfg.rope_scaling_dict)

    layer_fn = lambda x_, p_: _layer(cfg, x_, p_, cos, sin, mesh=mesh)
    if cfg.remat:
        # policy values are validated in __post_init__
        if cfg.remat_policy == "save_qkv":
            pol = jax.checkpoint_policies.save_only_these_names(
                "q_rope", "k_rope", "v_proj")
            ckpt_fn = jax.checkpoint(layer_fn, policy=pol)
        else:
            ckpt_fn = jax.checkpoint(layer_fn)
    else:
        ckpt_fn = layer_fn

    def scan_ckpt(x_, p_):
        return ckpt_fn(x_, p_), None

    n_store = min(cfg.remat_store_layers, cfg.num_layers) \
        if cfg.remat else 0
    if not cfg.scan_layers:
        if n_store > 0:
            raise ValueError(
                "scan_layers=False and remat_store_layers>0 conflict: "
                "partial remat is a scan-path knob (a silent fallback "
                "to scan would reintroduce the stacked-gradient "
                "re-copies unrolling opts out of)")
        # unrolled layer loop (see scan_layers in LlamaConfig)
        for l in range(cfg.num_layers):
            pl = jax.tree_util.tree_map(lambda a: a[l], params["layers"])
            x = ckpt_fn(x, pl)
    elif n_store <= 0:
        x, _ = jax.lax.scan(scan_ckpt, x, params["layers"])
    else:
        # Partial remat: the LAST n_store layers keep their internal
        # activations (no recompute in their backward) — recompute cost
        # drops by n_store/num_layers of a forward pass, paid in HBM.
        # Late layers are the right ones to store: their recompute would
        # otherwise sit on the critical path at the START of backward.
        split = cfg.num_layers - n_store
        head = jax.tree_util.tree_map(lambda a: a[:split],
                                      params["layers"])
        tail = jax.tree_util.tree_map(lambda a: a[split:],
                                      params["layers"])
        x, _ = jax.lax.scan(scan_ckpt, x, head)
        x, _ = jax.lax.scan(lambda x_, p_: (layer_fn(x_, p_), None),
                            x, tail)
    return _final_head(cfg, params, x)


def _final_head(cfg: LlamaConfig, params, x: jax.Array) -> jax.Array:
    """Shared model tail: final norm + (tied) LM head in fp32."""
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    return jnp.dot(x, head.astype(cfg.dtype),
                   preferred_element_type=jnp.float32)


def cross_entropy_loss(logits: jax.Array, targets: jax.Array,
                       mask: Optional[jax.Array] = None,
                       z_loss: float = 0.0) -> jax.Array:
    """Token-level CE in fp32 with optional z-loss regularization."""
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1
    )[..., 0]
    nll = lse - true_logit
    if z_loss:
        nll = nll + z_loss * jnp.square(lse)
    if mask is not None:
        nll = nll * mask
        return nll.sum() / jnp.maximum(mask.sum(), 1)
    return nll.mean()


def loss_fn(cfg: LlamaConfig, params, batch: Dict[str, jax.Array],
            mesh=None) -> jax.Array:
    """batch: {"tokens": [b, s]} — next-token prediction."""
    tokens = batch["tokens"]
    logits = forward(cfg, params, tokens[:, :-1], mesh=mesh)
    mask = batch.get("mask")
    if mask is not None:
        mask = mask[:, 1:]
    return cross_entropy_loss(logits, tokens[:, 1:], mask)


def loss_fn_pp(cfg: LlamaConfig, params, batch: Dict[str, jax.Array],
               mesh, num_microbatches: int) -> jax.Array:
    """Pipeline-parallel next-token loss: the layer stack is sharded over
    the mesh's ``pp`` axis and microbatches flow through a GPipe schedule
    compiled as ONE program (parallel/pipeline.py — shard_map + ppermute
    rotation; jax.grad reverses the schedule for the backward pass).

    Embed/head run replicated across pp (they are fsdp/tp-sharded by the
    usual rules); only the decoder blocks pipeline. num_microbatches must
    divide the batch and should be >> pp to amortize the bubble.
    """
    if cfg.remat_store_layers:
        raise ValueError(
            "remat_store_layers applies to the sequential forward only; "
            "under pipeline parallelism every stage is fully "
            "rematerialized (a silent no-op here would mislead tuning)")
    if cfg.remat_policy != "full" or not cfg.scan_layers:
        raise ValueError(
            "remat_policy/scan_layers are sequential-forward knobs; the "
            "pipeline schedule always scans stages under full remat — "
            "drop them rather than read tuning signal from a no-op")
    from jax.sharding import PartitionSpec as P

    try:
        shard_map = jax.shard_map
    except AttributeError:  # jax < 0.5: public alias not exported yet
        from jax.experimental.shard_map import shard_map

    # pp x sequence-parallel composition: pp OUTER (this shard_map), sp
    # INNER (ring_attention_local's KV blocks rotate on the sp sub-axis,
    # or ulysses_attention_local's all-to-alls run over it). Sequences
    # shard over sp; rope tables enter as sp-sharded inputs so each rank
    # holds its slice.
    seq_par = cfg.attn_impl in ("ring", "ulysses")
    sp = dict(getattr(mesh, "shape", {})).get("sp", 1)
    if seq_par and sp <= 1:
        raise ValueError(
            f"attn_impl={cfg.attn_impl!r} with pipeline parallelism "
            "requires a mesh with an 'sp' axis (> 1)")
    pp = dict(getattr(mesh, "shape", {})).get("pp", 1)
    if cfg.num_layers % max(pp, 1):
        raise ValueError(
            f"num_layers={cfg.num_layers} must divide the mesh's "
            f"pp={pp} (each stage holds num_layers/pp blocks)")

    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    b, s = inputs.shape
    M = num_microbatches
    assert b % M == 0, f"batch {b} must divide into {M} microbatches"
    x = params["embed"].astype(cfg.dtype)[inputs]
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
    cos, sin = rope_frequencies(cfg.head_dim_, s, cfg.rope_theta,
                                dtype=cfg.dtype,
                                scaling=cfg.rope_scaling_dict)
    mbs = x.reshape(M, b // M, s, cfg.hidden_size)

    seq_axis = "sp" if seq_par else None
    if seq_par and s % sp:
        raise ValueError(
            f"sequence length {s} must be divisible by the mesh's "
            f"sp={sp}")

    def layer_fn(x_, p_, cos_, sin_):
        return _layer(cfg, x_, p_, cos_, sin_, seq_axis=seq_axis)
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)

    def stage_fn_with_rope(cos_, sin_):
        def stage_fn(stage_layers, xmb):
            # this stage's L/P layers, leading axis scanned
            def body(x_, p_):
                return layer_fn(x_, p_, cos_, sin_), None

            out, _ = jax.lax.scan(body, xmb, stage_layers)
            return out
        return stage_fn

    def sharded_pipeline(stage_layers, mbs_rep, cos_, sin_):
        from ray_tpu.parallel.pipeline import pipeline_apply

        from ray_tpu.parallel.device_collectives import axis_size
        pp = axis_size("pp")
        outs = pipeline_apply(stage_fn_with_rope(cos_, sin_),
                              stage_layers, mbs_rep, "pp")
        # outputs live on the LAST stage; sum-rotate so every stage holds
        # them (cheap: one psum of zeros elsewhere)
        return jax.lax.psum(
            jnp.where(jax.lax.axis_index("pp") == pp - 1, outs, 0.0), "pp")

    layer_spec = P("pp")           # layer dim sharded over pp
    # REAL data parallelism alongside pp: the per-microbatch batch dim
    # shards over the mesh's data axes (each dp group pipelines its own
    # slice); activations stay replicated only across pp. With ring
    # attention the SEQUENCE dim additionally shards over sp, and each
    # rank receives its slice of the rope tables.
    data_axes = tuple(a for a in mesh.axis_names if a in ("dp", "fsdp"))
    mb_spec = P(None, data_axes if data_axes else None,
                "sp" if seq_par else None)
    rope_spec = P("sp" if seq_par else None)
    outs = shard_map(
        sharded_pipeline, mesh=mesh,
        in_specs=(layer_spec, mb_spec, rope_spec, rope_spec),
        out_specs=mb_spec,
        check_vma=False,
    )(params["layers"], mbs, cos, sin)

    x = outs.reshape(b, s, cfg.hidden_size)
    logits = _final_head(cfg, params, x)
    mask = batch.get("mask")
    if mask is not None:
        mask = mask[:, 1:]
    return cross_entropy_loss(logits, targets, mask)


def num_params(params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))


def param_shardings(cfg: LlamaConfig, mesh):
    """NamedSharding pytree for params on a given mesh."""
    from ray_tpu.parallel.sharding import shard_pytree_like

    return shard_pytree_like(logical_axes_without_layer(cfg), mesh)


def logical_axes_without_layer(cfg: LlamaConfig):
    """Logical axes with the stacked 'layer' dim mapped to None (pipeline
    parallelism later maps it to 'pp')."""
    return jax.tree_util.tree_map(
        lambda t: tuple(None if a == "layer" else a for a in t),
        logical_axes(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


def init_shapes(cfg: LlamaConfig):
    """ShapeDtypeStruct pytree matching init_params (for eval_shape uses)."""
    return jax.eval_shape(lambda: init_params(cfg, jax.random.PRNGKey(0)))
