"""KV-cached inference path for the Llama model: prefill + single-token
decode over a STATIC slot cache.

TPU-first design (none of this is in the reference — it serves via torch):
the serving cache is a fixed tensor ``[layers, slots, max_len, kv_heads,
head_dim]``. Every shape is static, so XLA compiles a handful of programs —
one prefill per bucket size, one decode chunk per size — and reuses them
for the lifetime of the server. Slot admission/eviction is pure
bookkeeping on the host. This dense path is the fastest at short
contexts (contiguous cache reads); models/llama_paged.py adds the paged
variant (page pool + block tables + prefix cache) for long/ragged
contexts and shared prompts.

Used by serve/llm_engine.py (continuous batching: new sequences join the
decode batch between steps by prefilling into a free slot).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig
from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies, swiglu


def init_cache(cfg: LlamaConfig, num_slots: int, max_len: int,
               mesh=None) -> Dict[str, jax.Array]:
    hd = cfg.head_dim_
    shape = (cfg.num_layers, num_slots, max_len, cfg.num_kv_heads, hd)
    cache = {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }
    if mesh is not None:
        cache = jax.device_put(cache, cache_shardings(cfg, mesh))
    return cache


def cache_shardings(cfg: LlamaConfig, mesh):
    """Slot-cache shardings for tensor-parallel decode: the KV-head axis
    of [L, S, T, KVH, hd] shards over ``tp`` (each chip owns its heads'
    cache — the per-chip HBM saving is the point of TP serving). When
    tp does not divide KVH (GQA with few KV heads), the cache replicates —
    the standard fallback; Q heads still split."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    tp = dict(getattr(mesh, "shape", {})).get("tp", 1)
    if tp > 1 and cfg.num_kv_heads % tp == 0:
        sh = NamedSharding(mesh, P(None, None, None, "tp", None))
    else:
        sh = NamedSharding(mesh, P())
    return {"k": sh, "v": sh}


def _project_qkv(cfg: LlamaConfig, p, x):
    """x [b, s, h] -> q [b,s,H,hd], k/v [b,s,KVH,hd] with rope NOT applied."""
    b, s, _ = x.shape
    hd = cfg.head_dim_
    h1 = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
    q = jnp.dot(h1, _w(p, "wq", cfg.dtype),
                preferred_element_type=jnp.float32).astype(cfg.dtype)
    k = jnp.dot(h1, _w(p, "wk", cfg.dtype),
                preferred_element_type=jnp.float32).astype(cfg.dtype)
    v = jnp.dot(h1, _w(p, "wv", cfg.dtype),
                preferred_element_type=jnp.float32).astype(cfg.dtype)
    if "bq" in p:  # Qwen2-style qkv biases
        q = q + p["bq"].astype(cfg.dtype)
        k = k + p["bk"].astype(cfg.dtype)
        v = v + p["bv"].astype(cfg.dtype)
    return (q.reshape(b, s, cfg.num_heads, hd),
            k.reshape(b, s, cfg.num_kv_heads, hd),
            v.reshape(b, s, cfg.num_kv_heads, hd), h1)


def _mlp(cfg: LlamaConfig, p, x):
    h2 = rms_norm(x, p["mlp_norm"], cfg.rms_norm_eps)
    return swiglu(h2, _w(p, "w_gate", cfg.dtype),
                  _w(p, "w_up", cfg.dtype), _w(p, "w_down", cfg.dtype),
                  act=cfg.mlp_act)


def _w(p, name: str, dtype):
    """Weight-leaf access: a plain array, or an int8 weight-only
    quantized leaf {"q": int8 [..., in, out], "s": f32 [..., 1, out]}
    dequantized on the fly. Decode is HBM-bandwidth-bound on weight
    reads; int8 halves that traffic and XLA fuses the convert+scale
    into the consuming dot's operand load."""
    v = p[name]
    if isinstance(v, dict):
        return v["q"].astype(dtype) * v["s"].astype(dtype)
    return v.astype(dtype)


# matmul weights eligible for weight-only quantization (biases, norms
# and the embedding gather stay in their original dtypes)
_QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_decode_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Per-output-channel symmetric int8 weight-only quantization of the
    decode params (serving only — training keeps full precision). Each
    [..., in, out] matmul weight becomes {"q": int8, "s": f32} with
    s = max|w| / 127 per output column. Quality: ~1e-2 relative logit
    error at 1B scale (see tests); throughput: weight HBM reads halve,
    which is the decode bottleneck."""

    def qz(w):
        w32 = w.astype(jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(w32), axis=-2, keepdims=True),
                        1e-8) / 127.0
        q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
        return {"q": q, "s": s}

    out = dict(params)
    layers = dict(params["layers"])
    for k in _QUANT_KEYS:
        if k in layers:
            layers[k] = qz(layers[k])
    out["layers"] = layers
    if "lm_head" in params:
        out["lm_head"] = qz(params["lm_head"])
    return out


def _prefill_attention(cfg: LlamaConfig, q, k, v):
    """Causal prefill attention: the Pallas flash kernel on TPU (GQA
    handled in-kernel, no repeated-KV materialization, no [b,H,P,P]
    score tensor), the fp32 reference path elsewhere. The kernel needs
    the sequence divisible by its block size, which holds for the
    power-of-two buckets but NOT the engine's max_len-1 overflow
    bucket — that one (and any other ragged length) silently takes the
    reference path instead of crashing at trace time."""
    from ray_tpu.ops.attention import attention_reference, flash_attention

    use_flash = cfg.prefill_flash
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    if use_flash and q.shape[1] % 128 == 0:
        return flash_attention(q, k, v, causal=True)
    return attention_reference(q, k, v, causal=True)


def prefill(cfg: LlamaConfig, params, tokens: jax.Array
            ) -> Tuple[jax.Array, Dict[str, jax.Array], jax.Array]:
    """Run the prompt through the model capturing per-layer K/V.

    tokens: [1, P] (P = padded bucket length).
    Returns (logits_last [vocab], kv {"k","v": [L, P, KVH, hd]},
    hidden-unused) — the engine inserts kv into a cache slot and samples
    the first generated token from logits_last at the true prompt length.
    """
    x = params["embed"].astype(cfg.dtype)[tokens]
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
    P = tokens.shape[1]
    cos, sin = rope_frequencies(cfg.head_dim_, P, cfg.rope_theta,
                                dtype=cfg.dtype,
                                scaling=cfg.rope_scaling_dict)

    def layer(x, p):
        b, s, _ = x.shape
        q, k, v, _ = _project_qkv(cfg, p, x)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = _prefill_attention(cfg, q, k, v)
        attn = attn.reshape(b, s, cfg.num_heads * cfg.head_dim_)
        x = x + jnp.dot(attn, _w(p, "wo", cfg.dtype),
                        preferred_element_type=jnp.float32).astype(cfg.dtype)
        x = x + _mlp(cfg, p, x)
        return x, (k[0], v[0])  # [P, KVH, hd]

    x, kv = jax.lax.scan(lambda x_, p_: layer(x_, p_), x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = (params["embed"].astype(cfg.dtype).T if cfg.tie_embeddings
            else _w(params, "lm_head", cfg.dtype))
    logits = jnp.dot(x[0], head,
                     preferred_element_type=jnp.float32)  # [P, vocab]
    return logits, {"k": kv[0], "v": kv[1]}, x


def prefill_batch(cfg: LlamaConfig, params, tokens: jax.Array,
                  last_idx: jax.Array
                  ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Batched prompt prefill: B prompts in one program.

    tokens: [B, P] (rows padded to the bucket length), last_idx: [B] (index
    of each row's true last prompt token). Returns (logits_last [B, vocab],
    kv {"k","v": [L, B, P, KVH, hd]}). One batched call replaces B
    sequential prefills — under burst admission this divides the
    prefill-phase host↔device round-trips by B (the tunnel RT dominates
    TTFT otherwise).
    """
    x = params["embed"].astype(cfg.dtype)[tokens]
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
    P = tokens.shape[1]
    cos, sin = rope_frequencies(cfg.head_dim_, P, cfg.rope_theta,
                                dtype=cfg.dtype,
                                scaling=cfg.rope_scaling_dict)

    def layer(x, p):
        b, s, _ = x.shape
        q, k, v, _ = _project_qkv(cfg, p, x)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = _prefill_attention(cfg, q, k, v)
        attn = attn.reshape(b, s, cfg.num_heads * cfg.head_dim_)
        x = x + jnp.dot(attn, _w(p, "wo", cfg.dtype),
                        preferred_element_type=jnp.float32).astype(cfg.dtype)
        x = x + _mlp(cfg, p, x)
        return x, (k, v)  # [B, P, KVH, hd]

    x, kv = jax.lax.scan(lambda x_, p_: layer(x_, p_), x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    # gather each row's last true prompt position, then ONE [B, vocab]
    # head matmul (a full [B, P, vocab] logits tensor would be ~P times
    # the transfer and FLOPs for the same information)
    B = tokens.shape[0]
    x_last = x[jnp.arange(B), last_idx]  # [B, h]
    head = (params["embed"].astype(cfg.dtype).T if cfg.tie_embeddings
            else _w(params, "lm_head", cfg.dtype))
    logits = jnp.dot(x_last, head,
                     preferred_element_type=jnp.float32)  # [B, vocab]
    return logits, {"k": kv[0], "v": kv[1]}


def insert_many(cache: Dict[str, jax.Array], kv: Dict[str, jax.Array],
                slots: jax.Array, valid: jax.Array
                ) -> Dict[str, jax.Array]:
    """Write B prefilled sequences into their cache slots in one program.

    kv: [L, B, P, KVH, hd]; slots [B] int32; valid [B] bool (padding rows
    of a partially-filled admission batch leave the cache untouched).
    """
    def body(cache, xs):
        k_row, v_row, slot, ok = xs   # k/v row: [L, P, KVH, hd]

        def write(c):
            k = jax.lax.dynamic_update_slice(
                c["k"], k_row[:, None], (0, slot, 0, 0, 0))
            v = jax.lax.dynamic_update_slice(
                c["v"], v_row[:, None], (0, slot, 0, 0, 0))
            return {"k": k, "v": v}

        return jax.lax.cond(ok, write, lambda c: c, cache), None

    cache, _ = jax.lax.scan(
        body, cache,
        (jnp.moveaxis(kv["k"], 1, 0), jnp.moveaxis(kv["v"], 1, 0),
         slots, valid))
    return cache


def insert_sequence(cache: Dict[str, jax.Array], kv: Dict[str, jax.Array],
                    slot: jax.Array) -> Dict[str, jax.Array]:
    """Write a prefilled sequence's K/V into cache slot ``slot``.
    kv arrays: [L, P, KVH, hd]; cache: [L, S, T, KVH, hd]. P <= T."""
    def write(c, s):
        # dynamic_update_slice at [0, slot, 0, 0, 0]
        return jax.lax.dynamic_update_slice(
            c, s[:, None], (0, slot, 0, 0, 0))
    return {"k": write(cache["k"], kv["k"]),
            "v": write(cache["v"], kv["v"])}


def decode_step(cfg: LlamaConfig, params, cache: Dict[str, jax.Array],
                tokens: jax.Array, positions: jax.Array,
                active: jax.Array
                ) -> Tuple[Dict[str, jax.Array], jax.Array]:
    """One token for every slot.

    tokens [S] int32 (last sampled token per slot), positions [S] int32
    (index the new token is written at), active [S] bool.
    Returns (cache, logits [S, vocab]).

    HBM discipline (the decode step is bandwidth-bound): attention runs
    over the OLD cache plus an explicit self-attention term for the
    in-flight token, so the big cache tensors are never rewritten by the
    attention path; the new K/V rows (L*S*KVH*hd elements, ~1 MB) land
    in ONE batched scatter at the end, which XLA performs in place on
    the donated cache. The previous design (scatter-then-attend via a
    full-width select inside the layer scan) rewrote the entire cache
    every step and measured 6.4 ms/step on v5e at 1B; this form measures
    ~3 ms — against a 2.3 ms weight-read floor.
    """
    S = tokens.shape[0]
    T = cache["k"].shape[2]
    hd = cfg.head_dim_
    x = params["embed"].astype(cfg.dtype)[tokens][:, None]  # [S, 1, h]
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
    cos_t, sin_t = rope_frequencies(hd, T, cfg.rope_theta,
                                    dtype=cfg.dtype,
                                    scaling=cfg.rope_scaling_dict)
    pos2 = positions[:, None]  # [S, 1] — per-slot rope positions

    # STRICT mask: history only; the current token's contribution enters
    # via the concatenated self-score below, not via the cache
    hist_mask = (jnp.arange(T)[None] < positions[:, None])  # [S, T]
    rep = cfg.num_heads // cfg.num_kv_heads

    def layer(carry, inp):
        x = carry
        p, ck, cv = inp
        q, k, v, _ = _project_qkv(cfg, p, x)     # q [S,1,H,hd], k/v [S,1,KVH,hd]
        q = apply_rope(q, cos_t, sin_t, positions=pos2)
        k = apply_rope(k, cos_t, sin_t, positions=pos2)
        k1, v1 = k[:, 0], v[:, 0]                # [S, KVH, hd]
        # GQA as a GROUPED einsum — no repeated-KV materialization (the
        # decode step is HBM-bound; repeating kv doubles cache traffic)
        q2 = q[:, 0].reshape(S, cfg.num_kv_heads, rep, hd)
        scores = jnp.einsum("skrd,stkd->skrt", q2, ck,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(hd))
        scores = jnp.where(hist_mask[:, None, None], scores, -1e30)
        self_s = jnp.einsum("skrd,skd->skr", q2, k1,
                            preferred_element_type=jnp.float32
                            ) / jnp.sqrt(jnp.float32(hd))
        scores = jnp.concatenate([scores, self_s[..., None]], axis=-1)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        attn = (jnp.einsum("skrt,stkd->skrd", probs[..., :T], cv)
                + probs[..., T][..., None] * v1[:, :, None, :])
        attn = attn.reshape(S, 1, cfg.num_heads * hd)
        x = x + jnp.dot(attn, _w(p, "wo", cfg.dtype),
                        preferred_element_type=jnp.float32).astype(cfg.dtype)
        x = x + _mlp(cfg, p, x)
        return x, (k1, v1)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"]))
    # new_k/new_v: [L, S, KVH, hd] — one scatter into the donated cache.
    # Inactive slots redirect to index T, dropped by mode="drop", so
    # their cache lines are untouched.
    scat = jnp.where(active, positions, T)
    ck = cache["k"].at[:, jnp.arange(S), scat].set(
        new_k, mode="drop", unique_indices=True)
    cv = cache["v"].at[:, jnp.arange(S), scat].set(
        new_v, mode="drop", unique_indices=True)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = (params["embed"].astype(cfg.dtype).T if cfg.tie_embeddings
            else _w(params, "lm_head", cfg.dtype))
    logits = jnp.dot(x[:, 0], head,
                     preferred_element_type=jnp.float32)  # [S, vocab]
    return {"k": ck, "v": cv}, logits


def sample_tokens(logits: jax.Array, key: jax.Array,
                  temperature: jax.Array, top_k: int = 0) -> jax.Array:
    """Per-slot sampling: temperature 0 means greedy; ``top_k`` (static,
    0 = off) masks everything below the k-th logit. logits [S, vocab],
    temperature [S]. Mixed batches work — each slot applies its own
    temperature, so greedy and sampled requests share one program."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, logits / temp,
                                     axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


def decode_chunk(cfg: LlamaConfig, params, cache: Dict[str, jax.Array],
                 tokens: jax.Array, positions: jax.Array, active: jax.Array,
                 num_steps: int, rng: Optional[jax.Array] = None,
                 temperature: Optional[jax.Array] = None, top_k: int = 0,
                 sample: bool = True
                 ) -> Tuple[Dict[str, jax.Array], jax.Array, jax.Array,
                            jax.Array]:
    """``num_steps`` decode steps in ONE device program.

    Amortizes host<->device dispatch latency (dominant over a remote
    tunnel) across many tokens: the sampled (or greedy) token feeds back
    on-device via lax.scan. Returns (cache, out_tokens [num_steps, S],
    next_tokens [S], next_positions [S]) — next_tokens/next_positions are
    PROGRAM OUTPUTS precisely so the engine can chain chunk N+1's inputs
    to chunk N's outputs as device arrays with no host round-trip (an
    eager ``out[-1]`` slice over a remote tunnel costs a full dispatch
    and was measured 3x slower than the chunk itself). Slots keep
    generating past EOS inside a chunk; the engine truncates host-side
    (bounded waste of num_steps-1 tokens per finished slot). With
    ``rng``/``temperature`` given, each slot samples at its own
    temperature (0 = greedy) with optional static top_k.
    """
    S = tokens.shape[0]
    if temperature is None:
        temperature = jnp.zeros((S,), jnp.float32)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def step(carry, _):
        cache, toks, pos, key = carry
        cache, logits = decode_step(cfg, params, cache, toks, pos, active)
        if sample:
            key, sub = jax.random.split(key)
            nxt = sample_tokens(logits, sub, temperature, top_k)
        else:
            # static greedy variant: no categorical, no top-k sort
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(active, nxt, toks)
        return (cache, nxt, pos + active.astype(jnp.int32), key), nxt

    (cache, nxt, pos, _), out = jax.lax.scan(
        step, (cache, tokens, positions, rng), None, length=num_steps)
    return cache, out, nxt, pos


def make_engine_fns(cfg: LlamaConfig, params, num_slots: int, max_len: int,
                    mesh=None):
    """Jitted (prefill_fn(tokens), insert_fn(cache, kv, slot),
    decode_fn(cache, tokens, positions, active)).

    params are passed as jit ARGUMENTS, never closed over: a closure would
    bake the full weight tensors into the HLO as literal constants and
    compilation explodes (GBs of literals). cfg is static (frozen
    dataclass).

    mesh: optional tensor-parallel mesh (axis "tp"). Weights shard the
    Megatron way — wq/wk/wv/w_gate/w_up column-wise, wo/w_down row-wise
    (the training logical-axis rules already say exactly this) — and XLA
    emits one all-reduce after attention and one after the MLP per layer,
    riding ICI on a real v5e-N slice. The KV cache shards over the KV-head
    axis (cache_shardings), so per-chip HBM holds 1/tp of the cache: the
    reason BASELINE config #5 serves on v5e-4 instead of one chip.
    Reference analogue (role, not design): torch_tensor_nccl_channel.py:191
    moving activations between TP shards; here the mesh IS the engine."""
    if mesh is not None:
        from ray_tpu.models import llama as _llama

        params = jax.device_put(params, _llama.param_shardings(cfg, mesh))
    prefill_b_j = jax.jit(prefill_batch, static_argnums=(0,))
    insert_many_j = jax.jit(insert_many, donate_argnums=(0,))
    decode_j = jax.jit(decode_step, static_argnums=(0,),
                       donate_argnums=(2,))
    chunk_j = jax.jit(decode_chunk, static_argnums=(0, 6, 9, 10),
                      donate_argnums=(2,))

    def pre_batch(tokens, last_idx):
        return prefill_b_j(cfg, params, tokens, last_idx)

    def dec(cache, tokens, positions, active):
        return decode_j(cfg, params, cache, tokens, positions, active)

    def dec_chunk(cache, tokens, positions, active, num_steps,
                  rng=None, temperature=None, top_k=0, sample=True):
        return chunk_j(cfg, params, cache, tokens, positions, active,
                       num_steps, rng, temperature, top_k, sample)

    return pre_batch, insert_many_j, dec, dec_chunk
