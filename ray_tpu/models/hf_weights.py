"""Load HuggingFace Llama-family checkpoints into ray_tpu param pytrees.

Reference role: the reference serves/trains models loaded from HF hubs
(e.g. python/ray/llm's engine configs name HF model ids); the TPU-native
equivalent maps the HF state dict onto this repo's stacked-layer pytree:

- torch ``nn.Linear`` stores [out, in] and computes ``x @ W.T``; our
  params store [in, out] and compute ``x @ W`` — every projection
  transposes on import.
- per-layer tensors stack along a leading layer axis (the model scans
  over it; pipeline parallelism shards it).
- rotary embeddings are split-half (GPT-NeoX convention) in BOTH
  implementations, so no head permutation is needed.

Use ``llama_from_hf`` with a transformers model, a state dict, or a
checkpoint path (anything ``LlamaForCausalLM.from_pretrained`` accepts).
Logit parity with the HF implementation is asserted in
tests/test_models.py.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple


def _parse_rope_scaling(hf_cfg):
    """llama3 / linear / yarn rope scaling are implemented
    (ops/layers.rope_frequencies); every other type refuses loudly —
    silently-wrong logits are worse than a load error."""
    scaling = getattr(hf_cfg, "rope_scaling", None)
    if not scaling:
        return None
    rope_type = scaling.get("rope_type") or scaling.get("type")
    if rope_type not in ("llama3", "linear", "yarn"):
        raise ValueError(
            f"unsupported HF config: rope_scaling type {rope_type!r} "
            f"(implemented: 'llama3', 'linear', 'yarn')")
    scaling = dict(scaling)
    if rope_type == "yarn" and not scaling.get(
            "original_max_position_embeddings"):
        # transformers falls back to the FIXED config length; pinning it
        # here keeps inv_freq identical across prefill/decode/training
        # table lengths (rope_frequencies would otherwise see each
        # call's max_seq_len)
        scaling["original_max_position_embeddings"] = \
            hf_cfg.max_position_embeddings
    return tuple(sorted(
        (k, v) for k, v in scaling.items() if v is not None))


def llama_config_from_hf(hf_cfg, attn_qkv_bias: bool = False) -> "Any":
    from ray_tpu.models.llama import LlamaConfig

    rope_scaling = _parse_rope_scaling(hf_cfg)
    if not attn_qkv_bias and (getattr(hf_cfg, "attention_bias", False)
                              or getattr(hf_cfg, "mlp_bias", False)):
        raise ValueError(
            "unsupported HF config: attention_bias/mlp_bias checkpoints "
            "carry bias tensors this model has no slots for")
    return LlamaConfig(
        attn_qkv_bias=attn_qkv_bias,
        vocab_size=hf_cfg.vocab_size,
        hidden_size=hf_cfg.hidden_size,
        intermediate_size=hf_cfg.intermediate_size,
        num_layers=hf_cfg.num_hidden_layers,
        num_heads=hf_cfg.num_attention_heads,
        num_kv_heads=getattr(hf_cfg, "num_key_value_heads", None)
        or hf_cfg.num_attention_heads,
        head_dim=getattr(hf_cfg, "head_dim", None),
        max_seq_len=hf_cfg.max_position_embeddings,
        rope_theta=float(getattr(hf_cfg, "rope_theta", 10000.0)),
        rms_norm_eps=float(hf_cfg.rms_norm_eps),
        tie_embeddings=bool(getattr(hf_cfg, "tie_word_embeddings", False)),
        rope_scaling=rope_scaling,
    )


def _fetcher(state_dict):
    """(t, lin): fetch-as-numpy, and torch-Linear-transposed fetch."""
    import numpy as np

    def t(name):
        v = state_dict[name]
        if hasattr(v, "detach"):
            v = v.detach().to("cpu").float().numpy()
        return np.asarray(v)

    def lin(name):  # torch Linear [out, in] -> ours [in, out]
        return t(name).T

    return t, lin


def _refuse_proj_bias(state_dict):
    bias_keys = [k for k in state_dict
                 if k.endswith(("proj.bias",)) and "layers" in k]
    if bias_keys:
        raise ValueError(
            f"unsupported checkpoint: projection bias tensors present "
            f"(e.g. {bias_keys[0]}) — this model implements bias-free "
            f"projections")


def _stack_attn(stacked, t, lin, prefix):
    """The llama-style attention block shared by Llama and Mixtral."""
    stacked["attn_norm"].append(t(prefix + "input_layernorm.weight"))
    stacked["wq"].append(lin(prefix + "self_attn.q_proj.weight"))
    stacked["wk"].append(lin(prefix + "self_attn.k_proj.weight"))
    stacked["wv"].append(lin(prefix + "self_attn.v_proj.weight"))
    stacked["wo"].append(lin(prefix + "self_attn.o_proj.weight"))
    stacked["mlp_norm"].append(
        t(prefix + "post_attention_layernorm.weight"))


def _assemble(cfg, stacked, t, lin, dtype):
    import numpy as np

    import jax.numpy as jnp

    params = {
        "embed": jnp.asarray(t("model.embed_tokens.weight"), dtype),
        "layers": {k: jnp.asarray(np.stack(v), dtype)
                   for k, v in stacked.items()},
        "final_norm": jnp.asarray(t("model.norm.weight"), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(lin("lm_head.weight"), dtype)
    return params


def llama_params_from_hf(state_dict: Dict[str, Any], cfg,
                         dtype=None) -> Dict[str, Any]:
    """HF Llama state dict (torch tensors or numpy) -> param pytree."""
    dtype = dtype or cfg.param_dtype
    t, lin = _fetcher(state_dict)
    _refuse_proj_bias(state_dict)
    stacked: Dict[str, list] = {k: [] for k in (
        "attn_norm", "wq", "wk", "wv", "wo", "mlp_norm", "w_gate",
        "w_up", "w_down")}
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        _stack_attn(stacked, t, lin, p)
        stacked["w_gate"].append(lin(p + "mlp.gate_proj.weight"))
        stacked["w_up"].append(lin(p + "mlp.up_proj.weight"))
        stacked["w_down"].append(lin(p + "mlp.down_proj.weight"))
    return _assemble(cfg, stacked, t, lin, dtype)


def gpt2_from_hf(source, dtype=None) -> Tuple[Any, Dict[str, Any]]:
    """(cfg, params) from a transformers GPT2LMHeadModel (or a checkpoint
    path/model id). GPT-2's HF weights use Conv1D layout [in, out] — the
    same orientation this repo uses, so tensors map 1:1 with only the
    per-layer stacking."""
    import numpy as np

    import jax.numpy as jnp

    from ray_tpu.models.gpt2 import GPT2Config

    if isinstance(source, str):
        from transformers import GPT2LMHeadModel

        source = GPT2LMHeadModel.from_pretrained(source)
    hf_cfg = source.config
    cfg = GPT2Config(vocab_size=hf_cfg.vocab_size,
                     hidden_size=hf_cfg.n_embd,
                     num_layers=hf_cfg.n_layer,
                     num_heads=hf_cfg.n_head,
                     max_seq_len=hf_cfg.n_positions,
                     ln_eps=float(hf_cfg.layer_norm_epsilon))
    if dtype is not None:
        from dataclasses import replace

        cfg = replace(cfg, param_dtype=dtype)
    sd = source.state_dict()
    t, _ = _fetcher(sd)

    names = {"ln1_g": "ln_1.weight", "ln1_b": "ln_1.bias",
             "w_qkv": "attn.c_attn.weight", "b_qkv": "attn.c_attn.bias",
             "w_proj": "attn.c_proj.weight", "b_proj": "attn.c_proj.bias",
             "ln2_g": "ln_2.weight", "ln2_b": "ln_2.bias",
             "w_fc": "mlp.c_fc.weight", "b_fc": "mlp.c_fc.bias",
             "w_out": "mlp.c_proj.weight", "b_out": "mlp.c_proj.bias"}
    pd = cfg.param_dtype if dtype is None else dtype
    layers = {ours: jnp.asarray(np.stack(
        [t(f"transformer.h.{i}.{hf}") for i in range(cfg.num_layers)]), pd)
        for ours, hf in names.items()}
    params = {
        "wte": jnp.asarray(t("transformer.wte.weight"), pd),
        "wpe": jnp.asarray(t("transformer.wpe.weight"), pd),
        "layers": layers,
        "lnf_g": jnp.asarray(t("transformer.ln_f.weight"), pd),
        "lnf_b": jnp.asarray(t("transformer.ln_f.bias"), pd),
    }
    return cfg, params


def llama_from_hf(source, dtype=None) -> Tuple[Any, Dict[str, Any]]:
    """(cfg, params) from a transformers model instance or a checkpoint
    path/model id loadable by ``LlamaForCausalLM.from_pretrained``."""
    if isinstance(source, str):
        from transformers import LlamaForCausalLM

        source = LlamaForCausalLM.from_pretrained(source)
    cfg = llama_config_from_hf(source.config)
    if dtype is not None:
        from dataclasses import replace

        cfg = replace(cfg, param_dtype=dtype)
    return cfg, llama_params_from_hf(source.state_dict(), cfg, dtype=dtype)


def mixtral_from_hf(source, dtype=None, capacity_factor=None
                    ) -> Tuple[Any, Dict[str, Any]]:
    """(cfg, params) from a transformers MixtralForCausalLM (or a
    checkpoint path/model id). Experts map w1->e_gate, w3->e_up,
    w2->e_down (Mixtral's naming), stacked [L, E, ...].

    NOTE on parity: this repo's MoE uses GShard-style STATIC-capacity
    dispatch (overflow drops); HF computes exact token-wise outputs.
    Pass ``capacity_factor >= num_experts/top_k`` for drop-free exact
    parity (the test does); production configs trade capacity for speed.
    """
    import numpy as np

    import jax.numpy as jnp

    from ray_tpu.models.mixtral import MixtralConfig

    if isinstance(source, str):
        from transformers import MixtralForCausalLM

        source = MixtralForCausalLM.from_pretrained(source)
    hf_cfg = source.config
    sw = getattr(hf_cfg, "sliding_window", None)
    if sw is not None and sw < hf_cfg.max_position_embeddings:
        raise ValueError(
            f"unsupported HF config: sliding_window={sw} (this model "
            f"implements full causal attention only; sequences past the "
            f"window would silently diverge from HF)")
    cfg = MixtralConfig(
        vocab_size=hf_cfg.vocab_size,
        hidden_size=hf_cfg.hidden_size,
        intermediate_size=hf_cfg.intermediate_size,
        num_layers=hf_cfg.num_hidden_layers,
        num_heads=hf_cfg.num_attention_heads,
        num_kv_heads=hf_cfg.num_key_value_heads,
        head_dim=getattr(hf_cfg, "head_dim", None),
        max_seq_len=hf_cfg.max_position_embeddings,
        rope_theta=float(hf_cfg.rope_theta),
        rms_norm_eps=float(hf_cfg.rms_norm_eps),
        tie_embeddings=bool(getattr(hf_cfg, "tie_word_embeddings", False)),
        num_experts=hf_cfg.num_local_experts,
        top_k=hf_cfg.num_experts_per_tok,
        rope_scaling=_parse_rope_scaling(hf_cfg),
    )
    from dataclasses import replace

    if dtype is not None:
        cfg = replace(cfg, param_dtype=dtype)
    if capacity_factor is not None:
        cfg = replace(cfg, capacity_factor=float(capacity_factor))
    sd = source.state_dict()
    t, lin = _fetcher(sd)
    _refuse_proj_bias(sd)
    pd = cfg.param_dtype  # replace() above already applied dtype
    L, E = cfg.num_layers, cfg.num_experts
    stacked: Dict[str, list] = {k: [] for k in (
        "attn_norm", "wq", "wk", "wv", "wo", "mlp_norm", "router",
        "e_gate", "e_up", "e_down")}
    for i in range(L):
        p = f"model.layers.{i}."
        _stack_attn(stacked, t, lin, p)
        moe = p + "block_sparse_moe."
        stacked["router"].append(lin(moe + "gate.weight"))
        stacked["e_gate"].append(np.stack(
            [lin(f"{moe}experts.{e}.w1.weight") for e in range(E)]))
        stacked["e_up"].append(np.stack(
            [lin(f"{moe}experts.{e}.w3.weight") for e in range(E)]))
        stacked["e_down"].append(np.stack(
            [lin(f"{moe}experts.{e}.w2.weight") for e in range(E)]))
    return cfg, _assemble(cfg, stacked, t, lin, pd)


def qwen2_from_hf(source, dtype=None) -> Tuple[Any, Dict[str, Any]]:
    """(cfg, params) from a transformers Qwen2ForCausalLM (or checkpoint
    path/model id). Qwen2 IS the llama block plus additive q/k/v biases
    (cfg.attn_qkv_bias), so the mapping is llama's + three bias stacks;
    o_proj/mlp remain bias-free and anything else refuses."""
    if isinstance(source, str):
        from transformers import Qwen2ForCausalLM

        source = Qwen2ForCausalLM.from_pretrained(source)
    hf_cfg = source.config
    sw = getattr(hf_cfg, "sliding_window", None)
    if getattr(hf_cfg, "use_sliding_window", False) and sw is not None \
            and sw < hf_cfg.max_position_embeddings:
        raise ValueError(
            f"unsupported HF config: sliding_window={sw} (full causal "
            f"attention only)")
    from dataclasses import replace

    cfg = llama_config_from_hf(hf_cfg, attn_qkv_bias=True)
    if dtype is not None:
        cfg = replace(cfg, param_dtype=dtype)
    sd = source.state_dict()
    bad = [k for k in sd if k.endswith(("o_proj.bias", "gate_proj.bias",
                                        "up_proj.bias", "down_proj.bias"))]
    if bad:
        raise ValueError(
            f"unsupported checkpoint: unexpected bias {bad[0]} (qwen2 "
            f"carries biases on q/k/v only)")
    t, lin = _fetcher(sd)
    pd = cfg.param_dtype
    stacked: Dict[str, list] = {k: [] for k in (
        "attn_norm", "wq", "wk", "wv", "wo", "mlp_norm", "w_gate",
        "w_up", "w_down", "bq", "bk", "bv")}
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        _stack_attn(stacked, t, lin, p)
        stacked["bq"].append(t(p + "self_attn.q_proj.bias"))
        stacked["bk"].append(t(p + "self_attn.k_proj.bias"))
        stacked["bv"].append(t(p + "self_attn.v_proj.bias"))
        stacked["w_gate"].append(lin(p + "mlp.gate_proj.weight"))
        stacked["w_up"].append(lin(p + "mlp.up_proj.weight"))
        stacked["w_down"].append(lin(p + "mlp.down_proj.weight"))
    return cfg, _assemble(cfg, stacked, t, lin, pd)


def gemma_from_hf(source, dtype=None) -> Tuple[Any, Dict[str, Any]]:
    """(cfg, params) from a transformers GemmaForCausalLM (or checkpoint
    path/model id). Gemma's deltas from the llama block, all absorbed
    here: GeGLU gate activation (cfg.mlp_act="gelu_tanh"), embeddings
    scaled by sqrt(hidden) at lookup (cfg.embed_scale), (1+w) RMSNorm —
    folded into the stored norm weights so the model code stays llama's
    — tied lm_head, and an explicit head_dim (256 on gemma-7b).
    Reference serves gemma via external engines; here it rides the same
    train/decode paths as llama."""
    import math as _math

    if isinstance(source, str):
        from transformers import GemmaForCausalLM

        source = GemmaForCausalLM.from_pretrained(source)
    hf_cfg = source.config
    from dataclasses import replace as _replace

    from ray_tpu.models.llama import LlamaConfig

    act = getattr(hf_cfg, "hidden_activation", None) or getattr(
        hf_cfg, "hidden_act", "gelu_pytorch_tanh")
    try:
        # "gelu" is transformers' EXACT erf GELU, not the tanh approx —
        # conflating them breaks parity at ~1e-3
        mlp_act = {"gelu_pytorch_tanh": "gelu_tanh", "gelu": "gelu"}[act]
    except KeyError:
        raise ValueError(
            f"unsupported gemma hidden activation {act!r}") from None
    cfg = LlamaConfig(
        vocab_size=hf_cfg.vocab_size,
        hidden_size=hf_cfg.hidden_size,
        intermediate_size=hf_cfg.intermediate_size,
        num_layers=hf_cfg.num_hidden_layers,
        num_heads=hf_cfg.num_attention_heads,
        num_kv_heads=getattr(hf_cfg, "num_key_value_heads", None)
        or hf_cfg.num_attention_heads,
        head_dim=getattr(hf_cfg, "head_dim", None),
        max_seq_len=hf_cfg.max_position_embeddings,
        rope_theta=float(getattr(hf_cfg, "rope_theta", 10000.0)),
        rms_norm_eps=float(hf_cfg.rms_norm_eps),
        tie_embeddings=True,  # gemma always ties lm_head to embeddings
        mlp_act=mlp_act,
        embed_scale=float(_math.sqrt(hf_cfg.hidden_size)),
    )
    if dtype is not None:
        cfg = _replace(cfg, param_dtype=dtype)
    state_dict = source.state_dict()
    t, lin = _fetcher(state_dict)
    _refuse_proj_bias(state_dict)
    stacked: Dict[str, list] = {k: [] for k in (
        "attn_norm", "wq", "wk", "wv", "wo", "mlp_norm", "w_gate",
        "w_up", "w_down")}
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        _stack_attn(stacked, t, lin, p)
        stacked["w_gate"].append(lin(p + "mlp.gate_proj.weight"))
        stacked["w_up"].append(lin(p + "mlp.up_proj.weight"))
        stacked["w_down"].append(lin(p + "mlp.down_proj.weight"))
    params = _assemble(cfg, stacked, t, lin, dtype or cfg.param_dtype)
    # gemma RMSNorm computes normed * (1 + w): fold the +1 in here so
    # ops/layers.rms_norm (normed * w) is exact
    params["layers"]["attn_norm"] = params["layers"]["attn_norm"] + 1
    params["layers"]["mlp_norm"] = params["layers"]["mlp_norm"] + 1
    params["final_norm"] = params["final_norm"] + 1
    return cfg, params


def hf_model_type(source) -> str:
    """The checkpoint's ``model_type`` WITHOUT loading weights (config
    only for a path/id) — callers can refuse unsupported architectures
    before paying a multi-GB download/instantiation."""
    if isinstance(source, str):
        from transformers import AutoConfig

        return AutoConfig.from_pretrained(source).model_type
    return source.config.model_type


def from_hf(source, dtype=None) -> Tuple[Any, Dict[str, Any]]:
    """Architecture-dispatching loader: llama / qwen2 / mixtral / gpt2
    by the checkpoint's ``model_type`` (reference role: engines resolve
    HF ids via AutoConfig). Accepts a model instance or a path/id."""
    if isinstance(source, str):
        from transformers import AutoConfig

        model_type = AutoConfig.from_pretrained(source).model_type
    else:
        model_type = source.config.model_type
    loader = {"llama": llama_from_hf, "qwen2": qwen2_from_hf,
              "gemma": gemma_from_hf,
              "mixtral": mixtral_from_hf, "gpt2": gpt2_from_hf}.get(
        model_type)
    if loader is None:
        raise ValueError(
            f"unsupported HF model_type {model_type!r} "
            f"(implemented: llama, qwen2, mixtral, gpt2)")
    return loader(source, dtype=dtype)
