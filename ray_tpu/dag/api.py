"""Compiled-DAG API: static actor graphs with resident loops.

Linear pipeline::

    dag = compile_pipeline([(actor1, "preprocess"), (actor2, "infer")])
    out = dag.execute(x)     # microsecond-scale dispatch per call
    dag.teardown()

General graphs (fan-out / fan-in, reference:
python/ray/dag/compiled_dag_node.py:482 + dag_node_operation.py)::

    with InputNode() as inp:
        a = bind(actor_a, "left", inp)
        b = bind(actor_b, "right", inp)
        c = bind(actor_c, "join", a, b)      # diamond
    dag = compile_dag(c)
    out = dag.execute(x)

Each stage's actor runs a resident loop (reference: the compiled DAG's
per-actor executable loop, compiled_dag_node.py:92) reading ALL its input
channels in a fixed order, invoking the bound method with those values,
and writing the result to every consumer's channel. Execution never
touches the scheduler. Same-node edges ride seqno-gated shm channels
(microseconds); CROSS-NODE edges ride framed TCP channels with the same
rendezvous semantics (dag/channel.py:SocketChannel), so a graph may span
the cluster. Stages run in PIPELINE: call N+1 may enter stage 1 while
call N is downstream.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import ray_tpu
from ray_tpu.core import runtime_context
from ray_tpu.core.config import config
from ray_tpu.dag.channel import (Channel, ChannelClosed, DeviceChannel,
                                 SocketChannel, open_endpoint)
from ray_tpu.exceptions import ActorError
from ray_tpu.util.debug_lock import make_lock


class InputNode:
    """Placeholder for the DAG input (parity with the reference's
    `with InputNode() as inp:` style)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class MultiOutputNode:
    """Declare several stages as the DAG's outputs; execute() returns a
    list in this order (reference: ray.dag.MultiOutputNode)."""

    def __init__(self, nodes: Sequence["_BoundStage"]):
        self.nodes = list(nodes)


class _BoundStage:
    __slots__ = ("actor", "method", "upstreams")

    def __init__(self, actor, method: str, upstreams):
        self.actor = actor
        self.method = method
        self.upstreams = list(upstreams)

    def experimental_compile(self, capacity: int = 1 << 20,
                             spin_us: Optional[int] = None,
                             device: Optional[str] = None) -> "CompiledDag":
        return compile_dag(self, capacity=capacity, spin_us=spin_us,
                           device=device)


def bind(actor, method: str, *upstreams) -> _BoundStage:
    """actor.method(*upstreams) as a DAG node; leaves are InputNodes."""
    if not upstreams:
        raise ValueError("bind needs at least one upstream")
    return _BoundStage(actor, method, upstreams)


def _actor_id_of(actor):
    return actor._actor_id if hasattr(actor, "_actor_id") else actor


class CompiledDag:
    """A compiled static graph. One channel per EDGE; the driver owns the
    input-edge writers and output-edge readers."""

    def __init__(self, output, capacity: int = 1 << 20,
                 spin_us: Optional[int] = None,
                 device: Optional[str] = None):
        outputs = (output.nodes if isinstance(output, MultiOutputNode)
                   else [output])
        if not outputs or not all(isinstance(o, _BoundStage)
                                  for o in outputs):
            raise ValueError("compile_dag needs _BoundStage output(s)")
        # channel-wait mode: busy-poll budget before the condvar fallback
        # (descriptors carry it, so stage loops and driver endpoints both
        # ride the spin lane); 0 = pure block
        self._spin_us = max(0, int(config.dag_spin_us if spin_us is None
                                   else spin_us))
        dev_mode = (config.dag_device_channels if device is None
                    else device)
        if dev_mode not in ("off", "auto", "force"):
            raise ValueError(
                f"dag_device_channels must be off/auto/force, "
                f"got {dev_mode!r}")
        core = runtime_context.get_core()
        self._core = core
        self._store = getattr(core, "store", None) \
            or getattr(core, "_home_store", None)
        self._kv = core.kv_op
        # socket-channel auth rides the cluster authkey; the driver holds
        # it programmatically (env may be unset in test drivers)
        self._chan_authkey = getattr(core, "_authkey", None)

        # ---- collect stages in topological order (DFS postorder) ----
        stages: List[_BoundStage] = []
        seen: Dict[int, bool] = {}

        def visit(node):
            if isinstance(node, InputNode):
                return
            if id(node) in seen:
                if not seen[id(node)]:
                    raise ValueError("DAG has a cycle")
                return
            seen[id(node)] = False
            for up in node.upstreams:
                visit(up)
            seen[id(node)] = True
            stages.append(node)

        for o in outputs:
            visit(o)
        self._stages = stages

        # ---- placement: which node hosts each endpoint ----
        def node_of(actor, method: str = "?") -> Any:
            import time as _time

            from ray_tpu.core.cluster.rpc import RpcError

            aid = _actor_id_of(actor)
            fn = getattr(core, "_actor_addr", None)
            if fn is None:
                return "local"  # embedded runtime: everything same-node
            # bounded retry on the TYPED lookup failures only (actor
            # registration racing compile, or a GCS blip): anything else
            # is a real bug and propagates immediately. An actor that
            # never appears within the deadline fails the COMPILE loudly
            # (a guessed host would surface as an undiagnosable
            # execute() timeout instead).
            wait_s = config.dag_compile_actor_wait_s
            deadline = _time.monotonic() + wait_s
            last: Any = None
            while True:
                try:
                    return tuple(fn(aid))
                except (ActorError, RpcError) as e:
                    last = e
                    if _time.monotonic() >= deadline:
                        break
                    _time.sleep(0.05)
            raise ValueError(
                f"cannot compile DAG: actor {aid} (stage .{method}) has "
                f"no known node after {wait_s:.1f}s "
                f"(dead, or never registered; raise "
                f"dag_compile_actor_wait_s if creation is slow): "
                f"{last!r}") from last

        # ---- device placement probe: (pid, is_tpu) per actor ----
        # jax Arrays can only be handed off by reference INSIDE one
        # process (one actor per worker), so a device edge requires both
        # stages bound to the same actor process.
        devinfo_cache: Dict[Any, tuple] = {}

        def devinfo(actor) -> tuple:
            aid = _actor_id_of(actor)
            if aid not in devinfo_cache:
                try:
                    ref = core.submit_actor_task(
                        aid, "__rtpu_dag_devinfo__", (), {}, 1)[0]
                    devinfo_cache[aid] = tuple(ray_tpu.get(ref, timeout=30))
                except Exception:  # noqa: BLE001 — probe is best-effort:
                    # any failure just means "no device edge", shm works
                    devinfo_cache[aid] = (None, False)
            return devinfo_cache[aid]
        driver_node = getattr(core, "_home", "local")
        if driver_node != "local":
            driver_node = tuple(driver_node)

        # ---- one channel per edge ----
        # edge key: (producer id | "input", consumer id); descriptor dicts
        # are shipped to the stage loops. Driver-attached edges to SAME
        # node use shm; everything else (incl. actor<->actor off the
        # driver's node) uses socket channels — shm needs both ends
        # mapped into the driver's arena.
        self._in_edges: List[Any] = []      # driver-side writer endpoints
        self._out_edges: List[Any] = []     # driver-side reader endpoints
        stage_in: Dict[int, List] = {id(s): [] for s in stages}
        stage_out: Dict[int, List] = {id(s): [] for s in stages}

        def make_edge(prod_node, cons_node, prod_actor=None,
                      cons_actor=None):
            same = (prod_node == cons_node == driver_node
                    or prod_node == cons_node == "local")
            if same and self._store is not None:
                # on-device edge: both stages in ONE actor process, on a
                # TPU backend ('force' skips the backend check so the
                # handoff is testable under JAX_PLATFORMS=cpu); anything
                # else transparently falls back to a plain shm channel
                if (dev_mode != "off" and prod_actor is not None
                        and cons_actor is not None):
                    p_pid, p_tpu = devinfo(prod_actor)
                    c_pid, c_tpu = devinfo(cons_actor)
                    if (p_pid is not None and p_pid == c_pid
                            and (dev_mode == "force"
                                 or (p_tpu and c_tpu))):
                        dch = DeviceChannel.create(self._store, capacity,
                                                   self._spin_us)
                        return dch.descriptor(), dch
                ch = Channel.create(self._store, capacity, self._spin_us)
                return ch.descriptor(), ch
            # descriptor carries the READER's (consumer's) node host: the
            # reader publishes only its port to the KV
            host = (cons_node[0] if isinstance(cons_node, tuple)
                    else "127.0.0.1")
            cid = SocketChannel.create_id()
            return ("sock", cid, host), None

        self._shm_chans: List[Channel] = []
        self._inputs: List[Any] = []
        self._outputs: List[Any] = []
        try:
            for s in stages:
                s_node = node_of(s.actor, s.method)
                for up in s.upstreams:
                    if isinstance(up, InputNode):
                        desc, ch = make_edge(driver_node, s_node)
                        stage_in[id(s)].append(desc)
                        self._in_edges.append((desc, ch))
                    else:
                        desc, ch = make_edge(node_of(up.actor, up.method),
                                             s_node, prod_actor=up.actor,
                                             cons_actor=s.actor)
                        stage_in[id(s)].append(desc)
                        stage_out[id(up)].append(desc)
                        if ch is not None:
                            self._shm_chans.append(ch)
            for o in outputs:
                desc, ch = make_edge(node_of(o.actor, o.method),
                                     driver_node)
                stage_out[id(o)].append(desc)
                self._out_edges.append((desc, ch))

            # Separate writer/reader locks: a write blocked on the input
            # channel's ack gate (pipeline at capacity) must not stop a
            # reader from draining the output channel — that drain is
            # what unblocks it. Routed through the lock factory so
            # RTPU_SANITIZE=1 puts this pairing under the runtime
            # lock-order sanitizer.
            self._wlock = make_lock("dag.CompiledDag._wlock")
            self._rlock = make_lock("dag.CompiledDag._rlock")
            self._down = False
            self._broken = False
            self._n_out = len(outputs)
            self._single = not isinstance(output, MultiOutputNode)

            # ---- start the resident loops ----
            acks = []
            for s in stages:
                acks.append(core.submit_actor_task(
                    _actor_id_of(s.actor), "__rtpu_dag_start__",
                    (stage_in[id(s)], stage_out[id(s)], s.method),
                    {}, 1)[0])
            for ref in acks:
                assert ray_tpu.get(ref, timeout=60) == "ok"

            # driver endpoints (socket endpoints rendezvous lazily; stage
            # loops are already up, so their reader sides publish;
            # appended one at a time so a failed rendezvous can still
            # release the endpoints opened before it)
            for desc, ch in self._in_edges:
                self._inputs.append(
                    ch if ch is not None else
                    open_endpoint(desc, kv=self._kv, role="writer",
                                  authkey=self._chan_authkey))
            for desc, ch in self._out_edges:
                self._outputs.append(
                    ch if ch is not None else
                    open_endpoint(desc, kv=self._kv, role="reader",
                                  authkey=self._chan_authkey))
        except BaseException:
            # half-built DAG: teardown() never runs for an object whose
            # __init__ raised, so release every channel endpoint created
            # so far — their shm pins would otherwise outlive the failed
            # compile until store close
            edge_chs = [c for _, c in self._in_edges + self._out_edges
                        if c is not None] + self._shm_chans
            opened = [c for c in self._inputs + self._outputs
                      if all(c is not e for e in edge_chs)]
            for c in edge_chs + opened:
                try:
                    c.release()
                # rtpu-lint: disable=L4 — best-effort unwind of a failed
                # compile; the original error is what must surface
                except Exception:  # noqa: BLE001
                    pass
            raise

    # ------------------------------------------------------------- calls

    def _check_usable(self):
        if self._down:
            raise RuntimeError("DAG was torn down")
        if self._broken:
            raise RuntimeError(
                "DAG is broken (a previous call timed out, so the "
                "request/response pairing is no longer trustworthy); "
                "teardown and recompile")

    def _read_outs(self, timeout_ms: int):
        """FIFO-ordered output read; a timeout poisons the DAG — the
        unconsumed in-flight result would otherwise be returned to the
        NEXT caller (off-by-one forever)."""
        vals = []
        try:
            for ch in self._outputs:
                vals.append(ch.read(timeout_ms=timeout_ms))
        except TimeoutError:
            self._broken = True
            raise
        return vals

    def execute(self, value: Any, timeout_ms: int = 60_000) -> Any:
        """Synchronous call through the graph."""
        from ray_tpu.dag.channel import _chan_dumps

        data = _chan_dumps(("v", value))  # serialize ONCE for the fan-out
        with self._wlock:
            self._check_usable()
            for ch in self._inputs:
                ch.write_raw(data, timeout_ms=timeout_ms)
        with self._rlock:
            outs = self._read_outs(timeout_ms)
        vals = []
        for tag, out in outs:
            if tag == "e":
                raise out
            vals.append(out)
        return vals[0] if self._single else vals

    def execute_async(self, value: Any, timeout_ms: int = 60_000):
        """Returns a 0-arg callable resolving the result (the next read).
        Calls resolve in FIFO order; useful to overlap pipeline stages."""
        from ray_tpu.dag.channel import _chan_dumps

        data = _chan_dumps(("v", value))
        with self._wlock:
            self._check_usable()
            for ch in self._inputs:
                ch.write_raw(data, timeout_ms=timeout_ms)

        def resolve():
            with self._rlock:
                outs = self._read_outs(timeout_ms)
            vals = []
            for tag, out in outs:
                if tag == "e":
                    raise out
                vals.append(out)
            return vals[0] if self._single else vals
        return resolve

    def teardown(self):
        with self._wlock:
            if self._down:
                return
            self._down = True
        try:
            for ch in self._inputs:
                ch.close()
            # close sentinels cascade through every stage loop; drain
            # each output until ITS sentinel (ChannelClosed) arrives —
            # pipelined calls still in flight at teardown would otherwise
            # leave sealed messages (and their shm slots) behind, since a
            # single read consumes at most one of them
            with self._rlock:
                for ch in self._outputs:
                    try:
                        while True:
                            ch.read(timeout_ms=5000)
                    except ChannelClosed:
                        pass  # fully drained
                    except Exception:  # noqa: BLE001 — draining best-effort
                        pass
        finally:
            with self._rlock:
                chans = self._inputs + self._outputs + self._shm_chans
            for ch in chans:
                ch.release()


def compile_dag(output, capacity: int = 1 << 20,
                spin_us: Optional[int] = None,
                device: Optional[str] = None) -> CompiledDag:
    """Compile a bound graph (single output node or MultiOutputNode).

    ``spin_us`` is the per-wait busy-poll budget before the condvar
    fallback (None = ``config.dag_spin_us``; 0 = pure block).
    ``device`` selects on-device edges: off/auto/force
    (None = ``config.dag_device_channels``)."""
    return CompiledDag(output, capacity=capacity, spin_us=spin_us,
                       device=device)


def compile_pipeline(stages: Sequence[Tuple[Any, str]],
                     capacity: int = 1 << 20,
                     spin_us: Optional[int] = None,
                     device: Optional[str] = None) -> CompiledDag:
    """Linear chain convenience over compile_dag."""
    node: Any = InputNode()
    for actor, method in stages:
        node = _BoundStage(actor, method, [node])
    return compile_dag(node, capacity=capacity, spin_us=spin_us,
                       device=device)
