"""Compiled-DAG API: static actor pipelines with resident loops.

Usage::

    dag = compile_pipeline([(actor1, "preprocess"), (actor2, "infer")])
    out = dag.execute(x)     # microsecond-scale dispatch per call
    dag.teardown()

Each stage's actor starts a resident thread (reference: the compiled DAG's
per-actor executable loop, python/ray/dag/compiled_dag_node.py:92) reading
its input channel, invoking the bound method, and writing the output
channel. Execution never touches the scheduler: values hop through
seqno-gated shm channels. Stages run in PIPELINE: call N+1 may enter stage
1 while call N is in stage 2.

Current scope: all actors on the driver's node (channels live in the
node's shm store); the driver core must own a store (embedded runtime or
same-host cluster driver).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Sequence, Tuple

import ray_tpu
from ray_tpu.core import runtime_context
from ray_tpu.dag.channel import Channel, ChannelClosed


class InputNode:
    """Placeholder for the DAG input (parity with the reference's
    `with InputNode() as inp:` style)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _BoundStage:
    __slots__ = ("actor", "method", "upstream")

    def __init__(self, actor, method: str, upstream):
        self.actor = actor
        self.method = method
        self.upstream = upstream

    def experimental_compile(self, capacity: int = 1 << 20
                             ) -> "CompiledPipeline":
        """Walk the bind chain back to the InputNode and compile."""
        stages: List[Tuple[Any, str]] = []
        node: Any = self
        while isinstance(node, _BoundStage):
            stages.append((node.actor, node.method))
            node = node.upstream
        if not isinstance(node, InputNode):
            raise ValueError("pipeline must terminate at an InputNode")
        stages.reverse()
        return compile_pipeline(stages, capacity=capacity)


def bind(actor, method: str, upstream) -> _BoundStage:
    """actor.method(upstream) as a DAG node; chain from an InputNode."""
    return _BoundStage(actor, method, upstream)


class CompiledPipeline:
    def __init__(self, stages: Sequence[Tuple[Any, str]],
                 capacity: int = 1 << 20):
        if not stages:
            raise ValueError("empty pipeline")
        core = runtime_context.get_core()
        store = getattr(core, "store", None)
        if store is None:
            raise RuntimeError(
                "compiled DAGs need a driver-side shm store (embedded "
                "runtime or same-host cluster driver)")
        self._store = store
        self._chans = [Channel.create(store, capacity)
                       for _ in range(len(stages) + 1)]
        # Separate writer/reader locks: a write blocked on the input
        # channel's ack gate (pipeline at capacity) must not stop a reader
        # from draining the output channel — that drain is what unblocks it.
        self._wlock = threading.Lock()
        self._rlock = threading.Lock()
        self._down = False
        self._broken = False
        # start each stage's resident loop
        acks = []
        for i, (actor, method) in enumerate(stages):
            acks.append(core.submit_actor_task(
                actor._actor_id if hasattr(actor, "_actor_id") else actor,
                "__rtpu_dag_start__",
                (self._chans[i].descriptor(),
                 self._chans[i + 1].descriptor(), method), {}, 1)[0])
        for ref in acks:
            assert ray_tpu.get(ref, timeout=60) == "ok"

    def _check_usable(self):
        if self._down:
            raise RuntimeError("pipeline was torn down")
        if self._broken:
            raise RuntimeError(
                "pipeline is broken (a previous call timed out, so the "
                "request/response pairing is no longer trustworthy); "
                "teardown and recompile")

    def _read_out(self, timeout_ms: int):
        """FIFO-ordered output read; a timeout poisons the pipeline — the
        unconsumed in-flight result would otherwise be returned to the
        NEXT caller (off-by-one forever)."""
        try:
            return self._chans[-1].read(timeout_ms=timeout_ms)
        except TimeoutError:
            self._broken = True
            raise

    def execute(self, value: Any, timeout_ms: int = 60_000) -> Any:
        """Synchronous call through the pipeline."""
        with self._wlock:
            self._check_usable()
            self._chans[0].write(("v", value), timeout_ms=timeout_ms)
        with self._rlock:
            tag, out = self._read_out(timeout_ms)
        if tag == "e":
            raise out
        return out

    def execute_async(self, value: Any, timeout_ms: int = 60_000):
        """Returns a 0-arg callable resolving the result (the next read).
        Calls resolve in FIFO order; useful to overlap pipeline stages."""
        with self._wlock:
            self._check_usable()
            self._chans[0].write(("v", value), timeout_ms=timeout_ms)

        def resolve():
            with self._rlock:
                tag, out = self._read_out(timeout_ms)
            if tag == "e":
                raise out
            return out
        return resolve

    def teardown(self):
        with self._wlock:
            if self._down:
                return
            self._down = True
        try:
            self._chans[0].close()
            # the close sentinel cascades through every stage loop
            with self._rlock:
                try:
                    self._chans[-1].read(timeout_ms=5000)
                except (ChannelClosed, TimeoutError):
                    pass
        finally:
            for ch in self._chans:
                ch.release()


def compile_pipeline(stages: Sequence[Tuple[Any, str]],
                     capacity: int = 1 << 20) -> CompiledPipeline:
    return CompiledPipeline(stages, capacity=capacity)
