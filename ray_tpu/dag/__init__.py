"""Compiled DAGs: µs-dispatch static actor pipelines over shm channels.

Capability analogue of the reference's accelerated/compiled DAGs
(python/ray/dag/compiled_dag_node.py:482) and mutable-object channels
(python/ray/experimental/channel/shared_memory_channel.py:147): a static
graph of actor method calls is "compiled" into resident per-actor loops
connected by seqno-gated mutable shm channels, so a steady-state pipeline
invocation costs microseconds of shm handoff instead of a scheduler round
trip per stage. This is the substrate Serve's TP/PP inference path uses.
"""

from ray_tpu.dag.api import (CompiledDag, InputNode,  # noqa: F401
                             MultiOutputNode, bind, compile_dag,
                             compile_pipeline)
from ray_tpu.dag.channel import Channel, SocketChannel  # noqa: F401
