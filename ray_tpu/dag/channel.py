"""Seqno-gated SPSC shm channel.

Layout inside one sealed store object (mutable by convention — the mapping
is shared read-write, the seal only fixes the allocation)::

    ChanHeader { seqno, ack, len, per-channel pshared mutex+cond }
    ...  payload (serialized container, <= capacity)

Single writer, single reader. The writer blocks until the previous message
is acked (rendezvous semantics, like the reference's mutable-object
channels, python/ray/experimental/channel/shared_memory_channel.py:147);
the reader blocks on seqno. Per-channel synchronization means a post wakes
exactly the peer — pipeline hops cost microseconds. Both sides use timed
waits so a dead peer surfaces as a timeout rather than a deadlock.
"""

from __future__ import annotations

import struct
from typing import Any, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.ids import ObjectID

_SEQ = 0  # counter index: writer publishes
_ACK = 1  # counter index: reader consumed


class ChannelClosed(Exception):
    pass


_CLOSE_LEN = (1 << 64) - 1  # len sentinel marking a closed channel


class Channel:
    """One endpoint of an SPSC channel (create on the writer side, open
    from a descriptor anywhere attached to the same store)."""

    def __init__(self, store, oid: ObjectID, capacity: int):
        self._store = store
        self._oid = oid
        self._capacity = capacity
        self._offset = store.object_offset(oid)  # pins the object
        self._hdr = store.chan_header_size()
        self._seq = 0   # last seqno this endpoint saw/wrote

    # -- construction --------------------------------------------------------

    @classmethod
    def create(cls, store, capacity: int = 1 << 20) -> "Channel":
        oid = ObjectID.from_random()
        hdr = store.chan_header_size()
        store.create_object(oid, hdr + capacity)
        store.seal(oid)
        ch = cls(store, oid, capacity)
        store.chan_init(ch._offset)
        return ch

    def descriptor(self) -> Tuple[bytes, int]:
        """Picklable descriptor; open with Channel.open on any process
        attached to the same store."""
        return (self._oid.binary(), self._capacity)

    @classmethod
    def open(cls, store, desc: Tuple[bytes, int]) -> "Channel":
        return cls(store, ObjectID(desc[0]), desc[1])

    # -- data plane ----------------------------------------------------------

    def _set_len(self, n: int):
        struct.pack_into(
            "<Q", self._store.view(self._offset + 16, 8), 0, n)

    def _get_len(self) -> int:
        return struct.unpack(
            "<Q", self._store.view(self._offset + 16, 8))[0]

    def write(self, value: Any, timeout_ms: int = 10_000):
        """Serialize + publish; blocks until the reader acked the previous
        message."""
        pickled, views, total = serialization.serialize(value)
        if total > self._capacity:
            raise ValueError(
                f"channel message ({total}B) exceeds capacity "
                f"({self._capacity}B)")
        # overwrite gate: previous message must be consumed
        if self._seq:
            acked = self._store.chan_wait(
                self._offset, _ACK, self._seq - 1, timeout_ms)
            if acked == 0:
                raise TimeoutError("channel reader did not ack in time")
        body = self._store.view(self._offset + self._hdr, total)
        serialization.write_container(body, pickled, views)
        self._set_len(total)
        self._seq += 1
        self._store.chan_post(self._offset, _SEQ, self._seq)

    def read(self, timeout_ms: int = 10_000) -> Any:
        """Block for the next message; deserializes a COPY (the slot is
        acked + reusable immediately after return)."""
        seq = self._store.chan_wait(self._offset, _SEQ, self._seq,
                                    timeout_ms)
        if seq == 0:
            raise TimeoutError("channel read timed out")
        self._seq = seq
        length = self._get_len()
        if length == _CLOSE_LEN:
            raise ChannelClosed
        data = bytes(self._store.view(self._offset + self._hdr, length))
        value = serialization.unpack(data)
        # ack: the writer may overwrite now
        self._store.chan_post(self._offset, _ACK, seq)
        return value

    def close(self, timeout_ms: int = 5000):
        """Writer-side: wake the reader with a close sentinel. Respects the
        ack gate so an unconsumed in-flight message is never clobbered."""
        if self._seq:
            # best effort: a dead reader must not make close() hang
            self._store.chan_wait(self._offset, _ACK, self._seq - 1,
                                  timeout_ms)
        self._set_len(_CLOSE_LEN)
        self._seq += 1
        self._store.chan_post(self._offset, _SEQ, self._seq)

    def release(self):
        try:
            self._store.release(self._oid)
        except Exception:  # noqa: BLE001
            pass
