"""Seqno-gated SPSC shm channel.

Layout inside one sealed store object (mutable by convention — the mapping
is shared read-write, the seal only fixes the allocation)::

    ChanHeader { seqno, ack, len, per-channel pshared mutex+cond }
    ...  payload (serialized container, <= capacity)

Single writer, single reader. The writer blocks until the previous message
is acked (rendezvous semantics, like the reference's mutable-object
channels, python/ray/experimental/channel/shared_memory_channel.py:147);
the reader blocks on seqno. Per-channel synchronization means a post wakes
exactly the peer — pipeline hops cost microseconds. Both sides use timed
waits so a dead peer surfaces as a timeout rather than a deadlock.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Tuple

from ray_tpu.core.ids import ObjectID
from ray_tpu.util.debug_lock import make_lock


def _chan_dumps(value: Any) -> bytes:
    try:
        return pickle.dumps(value, protocol=5)
    except Exception:  # noqa: BLE001 — closures etc.: cloudpickle path
        import cloudpickle

        return cloudpickle.dumps(value, protocol=5)

_SEQ = 0  # counter index: writer publishes
_ACK = 1  # counter index: reader consumed


class ChannelClosed(Exception):
    pass


_CLOSE_LEN = (1 << 64) - 1  # len sentinel marking a closed channel


class Channel:
    """One endpoint of an SPSC channel (create on the writer side, open
    from a descriptor anywhere attached to the same store)."""

    def __init__(self, store, oid: ObjectID, capacity: int,
                 spin_us: int = 0):
        self._store = store
        self._oid = oid
        self._capacity = capacity
        self._offset = store.object_offset(oid)  # pins the object
        self._hdr = store.chan_header_size()
        self._seq = 0   # last seqno this endpoint saw/wrote
        # busy-poll budget before the condvar fallback (0 = pure block);
        # carried in the descriptor so BOTH endpoints of a hot edge spin
        self._spin_us = int(spin_us)

    # -- construction --------------------------------------------------------

    @classmethod
    def create(cls, store, capacity: int = 1 << 20,
               spin_us: int = 0) -> "Channel":
        oid = ObjectID.from_random()
        hdr = store.chan_header_size()
        store.create_object(oid, hdr + capacity)
        try:
            store.seal(oid)
            ch = cls(store, oid, capacity, spin_us)
            store.chan_init(ch._offset)
        except BaseException:
            # seal/pin/init failed mid-construction: abort the backing
            # object (drop the ref, then free) instead of stranding an
            # unsealed or unowned allocation until store close
            store.release(oid)
            store.delete(oid)
            raise
        return ch

    def descriptor(self) -> Tuple[str, bytes, int, int]:
        """Picklable descriptor; open with Channel.open on any process
        attached to the same store."""
        return ("shm", self._oid.binary(), self._capacity, self._spin_us)

    @classmethod
    def open(cls, store, desc) -> "Channel":
        if desc[0] == "shm":
            spin_us = desc[3] if len(desc) > 3 else 0
            return cls(store, ObjectID(desc[1]), desc[2], spin_us)
        return cls(store, ObjectID(desc[0]), desc[1])  # legacy 2-tuple

    def _wait(self, which: int, last: int, timeout_ms: int) -> int:
        if self._spin_us > 0:
            return self._store.chan_wait_spin(
                self._offset, which, last, timeout_ms, self._spin_us)
        return self._store.chan_wait(self._offset, which, last, timeout_ms)

    # -- data plane ----------------------------------------------------------

    def _set_len(self, n: int):
        struct.pack_into(
            "<Q", self._store.view(self._offset + 16, 8), 0, n)

    def _get_len(self) -> int:
        return struct.unpack(
            "<Q", self._store.view(self._offset + 16, 8))[0]

    def write(self, value: Any, timeout_ms: int = 10_000):
        """Serialize + publish; blocks until the reader acked the previous
        message. The data plane is the C pickler writing straight into the
        shm slot (a channel hop is latency-critical; the container format
        with OOB buffers buys nothing at message sizes a slot can hold),
        with cloudpickle as the fallback for closures/lambdas."""
        self.write_raw(_chan_dumps(value), timeout_ms)

    def write_raw(self, data: bytes, timeout_ms: int = 10_000):
        """Publish pre-pickled bytes (fan-out callers serialize ONCE)."""
        if len(data) > self._capacity:
            raise ValueError(
                f"channel message ({len(data)}B) exceeds capacity "
                f"({self._capacity}B)")
        # overwrite gate: previous message must be consumed
        if self._seq:
            acked = self._wait(_ACK, self._seq - 1, timeout_ms)
            if acked == 0:
                raise TimeoutError("channel reader did not ack in time")
        body = self._store.view(self._offset + self._hdr, len(data))
        body[:len(data)] = data
        self._set_len(len(data))
        self._seq += 1
        self._store.chan_post(self._offset, _SEQ, self._seq)

    def read(self, timeout_ms: int = 10_000) -> Any:
        """Block for the next message; deserializes a COPY (the slot is
        acked + reusable immediately after return)."""
        seq = self._wait(_SEQ, self._seq, timeout_ms)
        if seq == 0:
            raise TimeoutError("channel read timed out")
        self._seq = seq
        length = self._get_len()
        if length == _CLOSE_LEN:
            raise ChannelClosed
        value = pickle.loads(
            self._store.view(self._offset + self._hdr, length))
        # ack: the writer may overwrite now
        self._store.chan_post(self._offset, _ACK, seq)
        return value

    def close(self, timeout_ms: int = 5000):
        """Writer-side: wake the reader with a close sentinel. Respects the
        ack gate so an unconsumed in-flight message is never clobbered."""
        if self._seq:
            # best effort: a dead reader must not make close() hang
            self._wait(_ACK, self._seq - 1, timeout_ms)
        self._set_len(_CLOSE_LEN)
        self._seq += 1
        self._store.chan_post(self._offset, _SEQ, self._seq)

    def release(self):
        try:
            self._store.release(self._oid)
        except Exception:  # noqa: BLE001
            pass


# -- on-device channels -------------------------------------------------------
#
# Process-local handoff registry for DeviceChannel: jax Arrays passed by
# REFERENCE between stages of the same process (bound methods of one TPU
# actor), keyed (channel oid bytes, seqno) so pipelined messages never
# collide. Only a tiny doorbell record crosses shm.
_DEVICE_HANDOFF: dict = {}
_DEVICE_HANDOFF_LOCK = make_lock("dag.device_handoff")


def _is_device_array(value: Any) -> bool:
    """True for a jax Array (the only payload DeviceChannel keeps on
    device); anything else rides the inner pickled shm path."""
    try:
        import jax

        return isinstance(value, jax.Array)
    except Exception:  # noqa: BLE001 — jax absent: nothing is on-device
        return False


def _is_device_payload(value: Any) -> bool:
    """True when a stage payload can stay on device whole: a single jax
    Array, or a non-empty tuple/list of jax Arrays (multi-buffer handoffs
    like a KV page pair — pickling any element would defeat the edge)."""
    if _is_device_array(value):
        return True
    if isinstance(value, (tuple, list)) and value:
        return all(_is_device_array(v) for v in value)
    return False


def donating_jit(fn, donate_argnums=(0,)):
    """jit a stage method so the listed array arguments are DONATED: the
    consumer stage reuses the producer's device buffer in place instead
    of allocating a copy — the zero-copy half of a DeviceChannel hop
    (reference: pjit's donation_vector/rebase_donate_argnums machinery).
    On CPU jax warns and ignores donation; semantics are unchanged."""
    import jax

    return jax.jit(fn, donate_argnums=donate_argnums)


class DeviceChannel:
    """DAG edge whose payload stays on device: both stages are methods of
    the same TPU actor process, so the producer's output jax Array (or
    tuple of jax Arrays — e.g. a KV page pair from a disaggregated
    prefill) is handed off by reference through :data:`_DEVICE_HANDOFF`
    — donation semantics, the producer must not reuse the value after
    write — and only a ("d",) doorbell record crosses the inner shm
    channel.

    Non-array payloads (host values, ("e", exc) error records, the close
    sentinel) pass through the inner channel unchanged, so the stage loop
    is oblivious to the edge type. Opening both endpoints in DIFFERENT
    processes is a compile-placement bug and surfaces as a RuntimeError
    at read time (the registry is process-local by design)."""

    def __init__(self, inner: "Channel"):
        self._inner = inner
        self._key = inner._oid.binary()

    @classmethod
    def create(cls, store, capacity: int = 1 << 20,
               spin_us: int = 0) -> "DeviceChannel":
        return cls(Channel.create(store, capacity, spin_us))

    def descriptor(self) -> Tuple[str, tuple]:
        return ("dev", self._inner.descriptor())

    @classmethod
    def open(cls, store, desc) -> "DeviceChannel":
        return cls(Channel.open(store, desc[1]))

    def write(self, value: Any, timeout_ms: int = 10_000):
        if (isinstance(value, tuple) and len(value) == 2
                and value[0] == "v" and _is_device_payload(value[1])):
            seq = self._inner._seq + 1
            with _DEVICE_HANDOFF_LOCK:
                _DEVICE_HANDOFF[(self._key, seq)] = value[1]
            try:
                self._inner.write(("d", None), timeout_ms)
            except BaseException:
                with _DEVICE_HANDOFF_LOCK:
                    _DEVICE_HANDOFF.pop((self._key, seq), None)
                raise
            return
        self._inner.write(value, timeout_ms)

    def read(self, timeout_ms: int = 10_000) -> Any:
        value = self._inner.read(timeout_ms)
        if isinstance(value, tuple) and len(value) == 2 \
                and value[0] == "d":
            with _DEVICE_HANDOFF_LOCK:
                arr = _DEVICE_HANDOFF.pop(
                    (self._key, self._inner._seq), None)
            if arr is None:
                raise RuntimeError(
                    "DeviceChannel doorbell with no device buffer: reader "
                    "and writer are not in the same process (compile "
                    "placement bug — device edges require both stages on "
                    "one actor)")
            return ("v", arr)
        return value

    def close(self, timeout_ms: int = 5000):
        self._inner.close(timeout_ms)

    def release(self):
        with _DEVICE_HANDOFF_LOCK:
            for k in [k for k in _DEVICE_HANDOFF if k[0] == self._key]:
                del _DEVICE_HANDOFF[k]
        self._inner.release()


def _recv_n(conn, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ChannelClosed
        buf.extend(chunk)
    return bytes(buf)


def _mutual_auth(conn, authkey: bytes, role: str):
    """Mutual HMAC challenge/response keyed on the cluster authkey.

    Every other socket in the system (RpcServer, node/GCS links) rides
    multiprocessing.connection's authkey handshake; this gives DAG edges
    the same trust anchor so no unauthenticated peer can hijack an edge
    or feed the reader a crafted pickle. Both sides send their challenge
    first (no deadlock), then verify the peer's digest. The ROLE is bound
    into the MAC (reader answers with b"R"+challenge, expects b"W"+...)
    so a digest produced by one reader connection can never satisfy
    another reader connection's check — without this, two concurrent
    connections to the same reader form a reflection oracle."""
    import hashlib
    import hmac
    import os as _os

    my_tag, peer_tag = (b"R", b"W") if role == "reader" else (b"W", b"R")
    mine = _os.urandom(16)
    conn.sendall(mine)
    theirs = _recv_n(conn, 16)
    conn.sendall(hmac.new(authkey, my_tag + theirs,
                          hashlib.sha256).digest())
    answer = _recv_n(conn, 32)
    expect = hmac.new(authkey, peer_tag + mine, hashlib.sha256).digest()
    if not hmac.compare_digest(expect, answer):
        raise PermissionError("dag channel peer failed authkey handshake")


class SocketChannel:
    """SPSC channel over TCP for CROSS-NODE DAG edges (reference role:
    the multi-node channels of python/ray/experimental/channel/ — there
    NCCL/gRPC-backed, here a framed socket riding DCN).

    Rendezvous through the cluster KV: the READER binds an ephemeral port
    and publishes ``dagchan:<id> -> (host, port)``; the WRITER polls the
    key and connects. Connections complete a mutual HMAC handshake on the
    cluster authkey before any payload flows. Same rendezvous semantics as
    the shm channel: the writer blocks until the reader acked the previous
    message, so at most one message is in flight per edge and FIFO pairing
    is exact."""

    def __init__(self, chan_id: str, kv, role: str,
                 timeout_ms: int = 30_000, host: str = "127.0.0.1",
                 authkey: bytes = None):
        import socket as _socket

        assert role in ("reader", "writer")
        self._id = chan_id
        self._kv = kv          # kv(op, key, value=None) -> value
        self._role = role
        self._host = host      # reader's node host, set at COMPILE time
        if authkey is None:
            from ray_tpu.core.cluster.rpc import cluster_authkey

            authkey = cluster_authkey()
        self._authkey = authkey
        self._conn = None
        self._await_ack = False
        self._got_any = False  # reader: saw >=1 message on this conn
        self._sock = None
        if role == "reader":
            s = _socket.socket()
            s.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
            try:
                # listen only on the advertised interface; "" would accept
                # from any interface on multi-homed hosts
                s.bind((host, 0))
            except OSError:
                s.bind(("", 0))
            s.listen(4)
            self._sock = s
            # publish only the PORT: the HOST comes from the descriptor,
            # where the compiler wrote the node's advertised address
            # (gethostname() resolves to loopback on stock images and
            # would point cross-node writers at themselves)
            self._kv("put", f"dagchan:{chan_id}", s.getsockname()[1])

    @classmethod
    def create_id(cls) -> str:
        import os as _os

        return _os.urandom(8).hex()

    def descriptor(self) -> Tuple[str, str, str]:
        return ("sock", self._id, self._host)

    def _ensure_conn(self, timeout_ms: int):
        import socket as _socket
        import threading
        import time as _time

        if self._conn is not None:
            return
        if self._role == "reader":
            # keep accepting until an AUTHENTICATED peer connects, so a
            # stray probe can neither hijack the edge nor wedge it.
            # Handshakes run on their own threads: a silent probe holding
            # its connection open must not serialize behind the accept
            # loop and starve the legitimate writer.
            import queue as _queue

            deadline = (None if timeout_ms < 0
                        else _time.monotonic() + max(0.001, timeout_ms / 1000))
            won: "_queue.Queue" = _queue.Queue()

            def _try_auth(c):
                try:
                    c.settimeout(5.0)
                    _mutual_auth(c, self._authkey, "reader")
                    if getattr(won, "closed", False):
                        c.close()  # a winner was already adopted
                    else:
                        won.put(c)
                except Exception:  # noqa: BLE001 — unauthenticated peer
                    try:
                        c.close()
                    except Exception:  # noqa: BLE001
                        pass

            conn = None
            while conn is None:
                try:
                    conn = won.get_nowait()
                    break
                except _queue.Empty:
                    pass
                if deadline is not None and _time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"socket channel {self._id}: no authenticated "
                        f"writer connected")
                self._sock.settimeout(0.25)
                try:
                    c, _ = self._sock.accept()
                except (TimeoutError, OSError):
                    continue
                threading.Thread(target=_try_auth, args=(c,),
                                 daemon=True).start()
            # close runner-ups: exactly one authenticated peer per edge
            won.closed = True
            while True:
                try:
                    won.get_nowait().close()
                except (_queue.Empty, OSError):
                    break
        else:
            # rendezvous (KV publish) is prompt — bounded even for -1
            kv_deadline = _time.monotonic() + (
                30.0 if timeout_ms < 0 else timeout_ms / 1000)
            port = None
            while _time.monotonic() < kv_deadline:
                port = self._kv("get", f"dagchan:{self._id}")
                if port:
                    break
                _time.sleep(0.01)
            if not port:
                raise TimeoutError(
                    f"socket channel {self._id}: reader never published")
            # retry transient handshake timeouts (reader busy vetting a
            # probe, or not accept()ing yet because its stage is blocked
            # downstream) until the caller's deadline; timeout_ms=-1 means
            # BLOCK — stage loops legitimately wait minutes on slow
            # downstreams. A wrong key fails fast.
            deadline = (None if timeout_ms < 0
                        else _time.monotonic() + timeout_ms / 1000)
            conn = None
            while True:
                c = None
                try:
                    c = _socket.create_connection(
                        (self._host, int(port)),
                        timeout=5.0 if deadline is None else
                        max(0.05, min(5.0, deadline - _time.monotonic())))
                    c.settimeout(5.0)
                    _mutual_auth(c, self._authkey, "writer")
                    conn = c
                    break
                except ConnectionRefusedError:
                    # the reader binds BEFORE publishing its port, so a
                    # refusal means it died — fail, don't spin (matters
                    # for timeout_ms=-1, which has no deadline)
                    raise ChannelClosed(
                        f"socket channel {self._id}: reader is gone")
                except PermissionError:
                    # wrong authkey (or EPERM from connect itself, in
                    # which case c is still None): fail fast, no retry
                    if c is not None:
                        c.close()
                    raise
                except Exception:  # noqa: BLE001 — timeout / peer reset
                    try:
                        c.close()
                    except Exception:  # noqa: BLE001
                        pass
                    if deadline is not None \
                            and _time.monotonic() >= deadline:
                        raise TimeoutError(
                            f"socket channel {self._id}: handshake never "
                            f"completed")
                    _time.sleep(0.05)
        # drop the handshake timeout: sends must honor the caller's
        # timeout_ms semantics (-1 = block), not a 5s auth cap
        conn.settimeout(None if timeout_ms < 0 else timeout_ms / 1000)
        conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self._conn = conn

    def _recv_exact(self, n: int, timeout_ms: int) -> bytes:
        self._conn.settimeout(None if timeout_ms < 0
                              else max(0.001, timeout_ms / 1000))
        buf = bytearray()
        while len(buf) < n:
            chunk = self._conn.recv(n - len(buf))
            if not chunk:
                raise ChannelClosed
            buf.extend(chunk)
        return bytes(buf)

    def write(self, value: Any, timeout_ms: int = 10_000):
        self.write_raw(_chan_dumps(value), timeout_ms)

    def write_raw(self, data: bytes, timeout_ms: int = 10_000):
        self._ensure_conn(timeout_ms)
        if self._await_ack:
            if self._recv_exact(1, timeout_ms) != b"A":
                raise ChannelClosed
            self._await_ack = False
        self._conn.sendall(struct.pack("<Q", len(data)) + data)
        self._await_ack = True

    def read(self, timeout_ms: int = 10_000) -> Any:
        import time as _time

        deadline = (None if timeout_ms < 0
                    else _time.monotonic() + timeout_ms / 1000)
        while True:
            self._ensure_conn(timeout_ms)
            try:
                (length,) = struct.unpack(
                    "<Q", self._recv_exact(8, timeout_ms))
                break
            except ChannelClosed:
                # EOF before the FIRST message: the adopted connection's
                # writer abandoned its handshake attempt (auth-timeout
                # race) and is retrying — fall back to accepting instead
                # of wedging the edge. EOF after traffic is a real close.
                if (self._role == "reader" and not self._got_any
                        and self._sock is not None
                        and (deadline is None
                             or _time.monotonic() < deadline)):
                    try:
                        self._conn.close()
                    except Exception:  # noqa: BLE001
                        pass
                    self._conn = None
                    continue
                raise
            except OSError as e:
                raise TimeoutError(f"socket channel read: {e}") from e
        if length == _CLOSE_LEN:
            raise ChannelClosed
        data = self._recv_exact(length, timeout_ms)
        value = pickle.loads(data)
        self._conn.sendall(b"A")
        self._got_any = True
        return value

    def close(self, timeout_ms: int = 5000):
        try:
            self._ensure_conn(timeout_ms)
            if self._await_ack:
                self._recv_exact(1, timeout_ms)
                self._await_ack = False
            self._conn.sendall(struct.pack("<Q", _CLOSE_LEN))
        except Exception:  # noqa: BLE001 — dead peer: nothing to close
            pass

    def release(self):
        for s in (self._conn, self._sock):
            if s is not None:
                try:
                    s.close()
                except Exception:  # noqa: BLE001
                    pass
        if self._role == "reader":
            try:
                self._kv("del", f"dagchan:{self._id}")
            except Exception:  # noqa: BLE001
                pass


def open_endpoint(desc, store=None, kv=None, role: str = "reader",
                  timeout_ms: int = 30_000, authkey: bytes = None):
    """Open either channel kind from its descriptor."""
    if desc[0] == "sock":
        host = desc[2] if len(desc) > 2 else "127.0.0.1"
        return SocketChannel(desc[1], kv, role, timeout_ms, host=host,
                             authkey=authkey)
    if store is None:
        raise RuntimeError("shm channel endpoint needs a store")
    if desc[0] == "dev":
        return DeviceChannel.open(store, desc)
    return Channel.open(store, desc)
