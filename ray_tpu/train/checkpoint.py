"""Checkpoint handle: a directory plus a (possibly remote) filesystem.

Reference: python/ray/train/_checkpoint.py:56 — Checkpoint is a location
pointer, not a blob; frameworks (orbax, flax serialization, msgpack) write
the actual files. fsspec gives S3/GCS transparently.
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
import uuid
from typing import Iterator, Optional

import fsspec


class Checkpoint:
    """A reference to a checkpoint directory on some filesystem."""

    def __init__(self, path: str, filesystem: Optional[fsspec.AbstractFileSystem] = None):
        if filesystem is None:
            filesystem, path = _resolve(path)
        self.path = path
        self.filesystem = filesystem

    # -------------------------------------------------------- constructors
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(os.path.abspath(path), fsspec.filesystem("file"))

    # ------------------------------------------------------------- access
    def to_directory(self, path: Optional[str] = None) -> str:
        """Materialize the checkpoint into a local directory and return it."""
        if path is None:
            path = os.path.join(
                tempfile.gettempdir(), f"rtpu_ckpt_{uuid.uuid4().hex[:8]}")
        os.makedirs(path, exist_ok=True)
        if _is_local(self.filesystem):
            if os.path.abspath(self.path) != os.path.abspath(path):
                shutil.copytree(self.path, path, dirs_exist_ok=True)
        else:
            self.filesystem.get(self.path.rstrip("/") + "/", path, recursive=True)
        return path

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        """Local dirs are yielded in place (zero copy); remote ones are
        downloaded to a temp dir that is cleaned up on exit."""
        if _is_local(self.filesystem):
            yield self.path
        else:
            tmp = self.to_directory()
            try:
                yield tmp
            finally:
                shutil.rmtree(tmp, ignore_errors=True)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __reduce__(self):
        proto = getattr(self.filesystem, "protocol", "file")
        if isinstance(proto, (tuple, list)):
            proto = proto[0]
        uri = self.path if proto in ("file", "local") else f"{proto}://{self.path}"
        return (Checkpoint, (uri,))


def _is_local(fs) -> bool:
    proto = getattr(fs, "protocol", "file")
    if isinstance(proto, (tuple, list)):
        return "file" in proto or "local" in proto
    return proto in ("file", "local")


def _resolve(uri: str):
    if "://" in uri:
        fs, _, paths = fsspec.get_fs_token_paths(uri)
        return fs, paths[0] if isinstance(paths, list) else paths
    return fsspec.filesystem("file"), os.path.abspath(uri)
