"""DataParallelTrainer: SPMD train loop over a worker gang.

Reference: python/ray/train/data_parallel_trainer.py:25 +
base_trainer.py:567 (fit). Differences by design: fit() drives the gang
directly (Tune wraps trainers at its own layer, rather than every fit being
a Tune trial), and the data-parallel substrate is a JAX mesh, not a torch
process group.

Fault tolerance: FailureConfig(max_failures) — on worker death or loop
error the gang is torn down, rebuilt, and restarted from the latest
persisted checkpoint (reference semantics).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import BackendExecutor, TrainingWorkerError
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.checkpoint_manager import CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.result import Result
from ray_tpu.train.session import PreemptedError
from ray_tpu.train.storage import StorageContext

logger = logging.getLogger(__name__)


class DataParallelTrainer:
    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 metadata: Optional[Dict[str, Any]] = None):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or BackendConfig()
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint
        self.metadata = metadata or {}

    # ----------------------------------------------------------------- fit
    def fit(self) -> Result:
        storage = StorageContext(self.run_config.resolved_storage_path(),
                                 experiment_name=self.run_config.name)
        storage.ensure_trial_dir()
        ckpt_mgr = CheckpointManager(storage,
                                     self.run_config.checkpoint_config)
        max_failures = self.run_config.failure_config.max_failures
        failures = 0
        preemptions = 0
        latest_metrics: Dict[str, Any] = {}
        history: list = []
        elastic_stats: list = []
        last_error: Optional[BaseException] = None

        while True:
            executor = BackendExecutor(self.backend_config, self.scaling_config)
            try:
                executor.start()
                # resume from the newest CONSISTENT checkpoint: torn/
                # partial dirs (worker died mid-persist) are dropped with
                # a warning instead of crashing the restart
                resume = ckpt_mgr.latest_consistent() \
                    or self.resume_from_checkpoint
                executor.start_training(
                    self.train_loop_per_worker,
                    self.train_loop_config,
                    context_kwargs={
                        "trial_name": storage.trial_name,
                        "experiment_name": storage.experiment_name,
                        "trial_dir": storage.trial_path,
                        "metadata": self.metadata,
                    },
                    checkpoint_path=resume.path if resume else None,
                    dataset_shards=self._shard_datasets(
                        self.scaling_config.num_workers),
                    storage_info={
                        "storage_path": self.run_config.resolved_storage_path(),
                        "experiment_name": storage.experiment_name,
                        "trial_name": storage.trial_name,
                        "checkpoint_index_start": ckpt_mgr.next_index,
                    },
                    shard_fn=self._shard_datasets,
                )
                while True:
                    results = executor.get_next_results()
                    if results is None:
                        break
                    # rank-0 metrics are the canonical row (reference keeps
                    # per-rank results but reports rank 0 by default)
                    latest_metrics = results[0].metrics
                    history.append(latest_metrics)
                    ckpt_dirs = [r.checkpoint_dir for r in results
                                 if r.checkpoint_dir]
                    if ckpt_dirs:
                        ckpt_mgr.register_persisted(ckpt_dirs[0], latest_metrics)
                last_error = None
                break
            # rtpu-lint: disable=L4 — this handler IS the restart
            # machinery: the enclosing while-loop rebuilds the gang and
            # resumes from the latest consistent checkpoint (bounded by
            # max_failures / max_preemptions)
            except TrainingWorkerError as e:
                last_error = e
                if isinstance(e.__cause__, PreemptedError):
                    # scheduled eviction, not a fault: restart from the
                    # latest checkpoint without consuming max_failures
                    preemptions += 1
                    logger.warning(
                        "gang preempted (%d/%d); restarting from latest "
                        "checkpoint", preemptions,
                        self.run_config.failure_config.max_preemptions)
                    if preemptions > \
                            self.run_config.failure_config.max_preemptions:
                        break
                else:
                    failures += 1
                    logger.warning("training failed (%d/%d): %s",
                                   failures, max_failures, e)
                    if max_failures >= 0 and failures > max_failures:
                        break
            finally:
                elastic_stats.extend(executor.elastic_stats)
                executor.shutdown()

        return Result(metrics=latest_metrics,
                      checkpoint=ckpt_mgr.best,
                      error=last_error,
                      path=storage.trial_path,
                      metrics_history=history,
                      elastic_stats=elastic_stats)

    # ------------------------------------------------------------ datasets
    def _shard_datasets(self, n: int):
        if not self.datasets:
            return None
        shards = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            split = getattr(ds, "streaming_split", None)
            if callable(split):
                for rank, piece in enumerate(split(n, equal=True)):
                    shards[rank][name] = piece
            else:
                for rank in range(n):
                    shards[rank][name] = ds
        return shards


class JaxTrainer(DataParallelTrainer):
    """DataParallelTrainer preconfigured with the JAX backend
    (the analogue of the reference's TorchTrainer, train/torch/config.py:154,
    with the mesh in place of a NCCL process group)."""

    def __init__(self, train_loop_per_worker: Callable, *,
                 jax_config: Optional[JaxConfig] = None, **kwargs):
        kwargs.setdefault("backend_config", jax_config or JaxConfig())
        super().__init__(train_loop_per_worker, **kwargs)
