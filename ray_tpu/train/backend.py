"""Backend hooks: per-framework gang wiring.

Reference: python/ray/train/backend.py (Backend/BackendConfig) and
train/torch/config.py:154 (_TorchBackend wires torch.distributed). Here the
first-class backend is JAX: set up jax.distributed for multi-host TPU pods,
or a virtual CPU platform for tests, plus a host-level (DCN) collective
group for cross-gang reductions outside jitted programs.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from ray_tpu.train.worker_group import WorkerGroup


@dataclass
class BackendConfig:
    def backend_cls(self):
        return Backend


class Backend:
    """No-op base backend."""

    def on_start(self, worker_group: WorkerGroup, backend_config: "BackendConfig"):
        pass

    def on_training_start(self, worker_group: WorkerGroup,
                          backend_config: "BackendConfig"):
        pass

    def abort_collectives(self, worker_group: WorkerGroup, reason: str):
        """Elastic resize, step 1: unblock survivors stuck in in-flight
        collectives (they fail over to CollectiveAbortedError within a
        poll interval instead of stalling out the op timeout). Called
        with the gang still at its OLD generation."""

    def on_resize(self, worker_group: WorkerGroup,
                  backend_config: "BackendConfig"):
        """Elastic resize, step 2: re-wire the (already re-ranked) gang
        at its new world size and generation — re-join collective
        groups, refresh platform/distributed state on every worker
        (including workers added by a grow)."""

    def on_shutdown(self, worker_group: WorkerGroup):
        pass


@dataclass
class JaxConfig(BackendConfig):
    """JAX gang wiring.

    platform: 'tpu' (real chips), 'cpu' (virtual devices for tests), or None
        to inherit the ambient platform.
    cpu_devices_per_worker: when platform='cpu', how many virtual XLA host
        devices each worker exposes (xla_force_host_platform_device_count).
    distributed: initialize jax.distributed across the gang (multi-host TPU
        pods / multi-process CPU). Worker 0 is the coordinator.
    host_collectives: create a host-level collective group named 'train'
        over the gang (the DCN/GLOO-equivalent path).
    """

    platform: Optional[str] = None
    cpu_devices_per_worker: int = 1
    distributed: bool = False
    coordinator_port: int = 0  # 0 = pick a free port on rank 0's host
    host_collectives: bool = True

    def backend_cls(self):
        return _JaxBackend


def _setup_jax_platform(platform: Optional[str], n_cpu_devices: int):
    if platform == "cpu":
        import re

        # REPLACE any inherited device-count flag (the pytest conftest
        # exports one for the whole session; each gang worker must get its
        # own local count, not the driver's)
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_cpu_devices}"
        ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif platform == "tpu":
        os.environ.setdefault("JAX_PLATFORMS", "tpu")


def _pick_coordinator(port: int) -> str:
    """Runs on rank 0: its host + a concrete port (a free one when the
    config leaves port=0, so repeated gangs never collide)."""
    import socket

    from ray_tpu.core.cluster.rpc import pick_port

    host = socket.gethostname()
    return f"{host}:{port or pick_port()}"


def _init_jax_distributed(coordinator: str, num_processes: int, process_id: int):
    import jax

    try:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:
        # a reused worker process from an earlier gang: reset and rejoin
        if "already" not in str(e).lower():
            raise
        jax.distributed.shutdown()
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)


def _join_host_collective_group(world_size: int, rank: int, group_name: str,
                                generation: int = 0):
    from ray_tpu.parallel import collective

    collective.init_collective_group(world_size, rank, backend="host",
                                     group_name=group_name,
                                     generation=generation)


TRAIN_GROUP = "train"


class _JaxBackend(Backend):
    def on_start(self, worker_group: WorkerGroup, cfg: JaxConfig):
        from ray_tpu.train.session import _install_preemption_handler

        worker_group.execute(_setup_jax_platform, cfg.platform,
                             cfg.cpu_devices_per_worker)
        # TPU maintenance events arrive as SIGTERM: give every gang
        # worker a grace window to checkpoint (session.preempted())
        worker_group.execute(_install_preemption_handler)
        if cfg.distributed and len(worker_group) > 1:
            coordinator = worker_group.execute_single(
                0, _pick_coordinator, cfg.coordinator_port)
            import ray_tpu

            refs = [
                w.execute.remote(_init_jax_distributed, coordinator,
                                 len(worker_group), rank)
                for rank, w in enumerate(worker_group.workers)
            ]
            ray_tpu.get(refs)

    def _join_collectives(self, worker_group: WorkerGroup, cfg: JaxConfig):
        if cfg.host_collectives and len(worker_group) > 1:
            import ray_tpu

            refs = [
                w.execute.remote(_join_host_collective_group,
                                 len(worker_group), rank, TRAIN_GROUP,
                                 worker_group.generation)
                for rank, w in enumerate(worker_group.workers)
            ]
            ray_tpu.get(refs)

    def on_training_start(self, worker_group: WorkerGroup, cfg: JaxConfig):
        self._join_collectives(worker_group, cfg)

    def abort_collectives(self, worker_group: WorkerGroup, reason: str):
        from ray_tpu.parallel import collective

        collective.abort_group(TRAIN_GROUP, reason,
                               generation=worker_group.generation)

    def on_resize(self, worker_group: WorkerGroup, cfg: JaxConfig):
        from ray_tpu.parallel import collective
        from ray_tpu.train.session import _install_preemption_handler

        # the previous incarnation's (aborted) coordinator has been fully
        # drained by now; reclaim its name slot
        if worker_group.generation > 0:
            collective.destroy_coordinator(
                TRAIN_GROUP, generation=worker_group.generation - 1)
        # idempotent for survivors, required for grown-in workers
        worker_group.execute(_setup_jax_platform, cfg.platform,
                             cfg.cpu_devices_per_worker)
        worker_group.execute(_install_preemption_handler)
        if cfg.distributed and len(worker_group) > 1:
            coordinator = worker_group.execute_single(
                0, _pick_coordinator, cfg.coordinator_port)
            import ray_tpu

            refs = [
                w.execute.remote(_init_jax_distributed, coordinator,
                                 len(worker_group), rank)
                for rank, w in enumerate(worker_group.workers)
            ]
            ray_tpu.get(refs)
        self._join_collectives(worker_group, cfg)

    def on_shutdown(self, worker_group: WorkerGroup):
        from ray_tpu.parallel import collective

        # reclaim the current incarnation's coordinator so a later gang
        # (cold restart in the same runtime) starts from fresh,
        # un-aborted state
        collective.destroy_coordinator(
            TRAIN_GROUP, generation=worker_group.generation)
