"""Drives the worker gang through a training run.

Reference: python/ray/train/_internal/backend_executor.py:67 (start :129,
start_training :445). The executor owns the WorkerGroup, applies backend
hooks, fans the train loop out, and pumps synchronized result batches — one
TrainingResult per worker per report — back to the trainer.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import RayTpuError
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainingResult
from ray_tpu.train.worker_group import WorkerGroup


class TrainingWorkerError(RayTpuError):
    """A training worker died or its train loop raised."""


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig, scaling: ScalingConfig):
        self.backend_config = backend_config
        self.backend: Backend = backend_config.backend_cls()()
        self.scaling = scaling
        self.worker_group: Optional[WorkerGroup] = None

    def start(self):
        self.worker_group = WorkerGroup(self.scaling)
        self.worker_group.start()
        # rank/world-size env before any user code or jax import
        for rank, w in enumerate(self.worker_group.workers):
            w.set_env.remote({
                "RAY_TPU_RANK": str(rank),
                "RAY_TPU_WORLD_SIZE": str(self.scaling.num_workers),
            })
        self.backend.on_start(self.worker_group, self.backend_config)

    def start_training(self, train_fn: Callable, config: Dict[str, Any],
                       context_kwargs: Dict[str, Any],
                       checkpoint_path: Optional[str] = None,
                       dataset_shards: Optional[List[Dict[str, Any]]] = None,
                       storage_info: Optional[Dict[str, Any]] = None):
        assert self.worker_group is not None, "call start() first"
        self.backend.on_training_start(self.worker_group, self.backend_config)
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            shards = dataset_shards[rank] if dataset_shards else None
            refs.append(w.start_training.remote(
                train_fn, config, context_kwargs, checkpoint_path, shards,
                storage_info))
        ray_tpu.get(refs)

    def get_next_results(self) -> Optional[List[TrainingResult]]:
        """One synchronized batch: the next report from every worker.

        Returns None when all workers finished cleanly. Raises
        TrainingWorkerError when any worker errored (actor death or user
        exception), carrying the first underlying error.
        """
        assert self.worker_group is not None
        refs = [w.next_result.remote() for w in self.worker_group.workers]
        # Harvest as results land and FAIL FAST on the first error: when
        # one rank raises (user exception, PreemptedError after a
        # maintenance SIGTERM, actor death), its gang peers are typically
        # blocked inside a cross-process collective and will never report
        # — waiting for all refs would deadlock the driver. Teardown
        # (executor.shutdown on the error path) unblocks them by killing
        # the group.
        results: List[Optional[TrainingResult]] = [None] * len(refs)
        pending = list(refs)
        index = {r: i for i, r in enumerate(refs)}
        while pending:
            done_refs, pending = ray_tpu.wait(pending, num_returns=1)
            for ref in done_refs:
                try:
                    res: TrainingResult = ray_tpu.get(ref)
                except Exception as e:
                    raise TrainingWorkerError(
                        f"training worker died: {e}") from e
                if res.error is not None:
                    raise TrainingWorkerError(
                        f"train loop failed on a worker: {res.error!r}"
                    ) from res.error
                results[index[ref]] = res
        if all(r.done for r in results):
            return None
        # Mixed done/not-done means a worker returned early from its loop —
        # the remaining workers would deadlock on their next collective.
        if any(r.done for r in results):
            raise TrainingWorkerError(
                "some workers finished while others are still reporting — "
                "train_loop_per_worker must report the same number of times "
                "on every rank")
        return results

    def shutdown(self):
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group)
            self.worker_group.shutdown()
            self.worker_group = None
