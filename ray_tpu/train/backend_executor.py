"""Drives the worker gang through a training run.

Reference: python/ray/train/_internal/backend_executor.py:67 (start :129,
start_training :445). The executor owns the WorkerGroup, applies backend
hooks, fans the train loop out, and pumps synchronized result batches — one
TrainingResult per worker per report — back to the trainer.

Elastic gangs (ScalingConfig.min_workers set): a worker death — actor
death, injected preemption, or a PreemptedError raised by the loop after
a maintenance SIGTERM — is a RESIZE EVENT, not a run failure. The
executor aborts survivors' in-flight collectives (CollectiveAbortedError
within ~ms instead of the 120 s op timeout), interrupts and drains the
surviving sessions, tears down only the lost ranks, re-forms the gang at
the new world size (new collective generation, compacted ranks,
re-sharded data), and restarts every rank's loop from the last
CONSISTENT checkpoint — the newest one that every rank completed — so
the loss curve is step-for-step deterministic versus an uninterrupted
run. When capacity returns (bounded by min/max workers and the grow
cooldown), the gang grows back through the same path.
"""

from __future__ import annotations

import logging
import os
import signal
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.config import config
from ray_tpu.exceptions import ActorDiedError, ActorUnavailableError, \
    GetTimeoutError, RayTpuError, WorkerCrashedError
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import PreemptedError, TrainingResult
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)

# How long a survivor gets to unwind (report in flight -> interrupt
# observed -> done sentinel) before the executor gives up and treats it
# as dead too. Generous: the normal path completes in milliseconds.
_DRAIN_TIMEOUT_S = 15.0

_DEATH_ERRORS = (ActorDiedError, ActorUnavailableError, WorkerCrashedError)


class TrainingWorkerError(RayTpuError):
    """A training worker died or its train loop raised."""


class _GangResizeNeeded(Exception):
    """Internal: a harvest detected lost ranks in an elastic gang."""

    def __init__(self, dead: Dict[int, BaseException],
                 results: List[Optional[TrainingResult]],
                 pending_refs: Optional[Dict[int, Any]] = None):
        super().__init__(f"lost ranks {sorted(dead)}")
        self.dead = dead          # position -> underlying cause
        self.results = results    # partial harvest (per current position)
        # position -> the harvest's still-in-flight next_result ref. The
        # drain MUST consume these instead of issuing fresh calls: two
        # concurrent readers on one session would steal each other's
        # queue items (including the done sentinel).
        self.pending_refs = pending_refs or {}


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig, scaling: ScalingConfig):
        self.backend_config = backend_config
        self.backend: Backend = backend_config.backend_cls()()
        self.scaling = scaling
        self.worker_group: Optional[WorkerGroup] = None
        # -------- elastic state --------
        self._spec: Optional[Dict[str, Any]] = None  # captured training spec
        self._batch_index = 0                 # harvested batches this run
        self._consistent_ckpts: List[str] = []  # full-batch ckpt paths
        self._ckpt_index_next = 0
        self._last_resize_t = 0.0
        self.elastic_stats: List[Dict[str, Any]] = []

    @property
    def _elastic(self) -> bool:
        return self.scaling.elastic

    @property
    def _min_workers(self) -> int:
        return self.scaling.min_workers or self.scaling.num_workers

    @property
    def _target_workers(self) -> int:
        # the PG bounds growth to its bundle count regardless; max_workers
        # beyond num_workers only takes effect for bundle-less gangs
        return self.scaling.max_workers or self.scaling.num_workers

    def start(self):
        self.worker_group = WorkerGroup(self.scaling)
        self.worker_group.start()
        # rank/world-size env before any user code or jax import
        for rank, w in enumerate(self.worker_group.workers):
            w.set_env.remote({
                "RAY_TPU_RANK": str(rank),
                "RAY_TPU_WORLD_SIZE": str(self.scaling.num_workers),
            })
        self.backend.on_start(self.worker_group, self.backend_config)

    def start_training(self, train_fn: Callable, config_dict: Dict[str, Any],
                       context_kwargs: Dict[str, Any],
                       checkpoint_path: Optional[str] = None,
                       dataset_shards: Optional[List[Dict[str, Any]]] = None,
                       storage_info: Optional[Dict[str, Any]] = None,
                       shard_fn: Optional[Callable] = None):
        assert self.worker_group is not None, "call start() first"
        self._spec = {
            "train_fn": train_fn,
            "config": config_dict,
            "context_kwargs": context_kwargs,
            "checkpoint_path": checkpoint_path,
            "storage_info": storage_info,
            "shard_fn": shard_fn,
        }
        self._ckpt_index_next = (storage_info or {}).get(
            "checkpoint_index_start", 0)
        self.backend.on_training_start(self.worker_group, self.backend_config)
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            shards = dataset_shards[rank] if dataset_shards else None
            refs.append(w.start_training.remote(
                train_fn, config_dict, context_kwargs, checkpoint_path,
                shards, storage_info))
        ray_tpu.get(refs)

    # ------------------------------------------------------------ harvest
    def get_next_results(self) -> Optional[List[TrainingResult]]:
        """One synchronized batch: the next report from every worker.

        Returns None when all workers finished cleanly. Raises
        TrainingWorkerError when any worker errored (actor death or user
        exception), carrying the first underlying error. Elastic gangs
        absorb worker deaths/preemptions here by resizing and resuming;
        only a real user error, or shrinking below min_workers, raises.
        """
        assert self.worker_group is not None
        if not self._elastic:
            results = self._harvest()
            self._commit_batch(results)
            return results
        while True:
            self._maybe_grow()
            try:
                results = self._harvest()
            except _GangResizeNeeded as ev:
                self._resize(ev)
                continue
            self._commit_batch(results)
            return results

    def _commit_batch(self, results: Optional[List[TrainingResult]]):
        """Bookkeeping after a full-gang batch, then the chaos site."""
        if results is None:
            return
        idx = self._batch_index
        self._batch_index += 1
        ckpt_dirs = [r.checkpoint_dir for r in results if r.checkpoint_dir]
        if ckpt_dirs:
            # every rank reported this step: the persisted checkpoint is
            # CONSISTENT — a valid deterministic resume point
            self._consistent_ckpts.append(ckpt_dirs[0])
            self._ckpt_index_next += 1
        self._fire_gang_resize(str(idx))

    def _harvest(self) -> Optional[List[TrainingResult]]:
        wg = self.worker_group
        refs = [w.next_result.remote() for w in wg.workers]
        # Harvest as results land and FAIL FAST on the first error: when
        # one rank raises (user exception, PreemptedError after a
        # maintenance SIGTERM, actor death), its gang peers are typically
        # blocked inside a cross-process collective and will never report
        # — waiting for all refs would deadlock the driver. Non-elastic
        # teardown (executor.shutdown on the error path) unblocks them by
        # killing the group; elastic gangs unblock them via the
        # collective abort inside _resize.
        results: List[Optional[TrainingResult]] = [None] * len(refs)
        pending = list(refs)
        index = {r: i for i, r in enumerate(refs)}
        dead: Dict[int, BaseException] = {}
        while pending:
            done_refs, pending = ray_tpu.wait(pending, num_returns=1)
            for k, ref in enumerate(done_refs):
                pos = index[ref]
                # refs the resize's drain must take over (everything not
                # consumed yet, minus the one that just failed)
                unharvested = {index[r]: r
                               for r in list(done_refs[k + 1:]) + pending}
                try:
                    res: TrainingResult = ray_tpu.get(ref)
                except _DEATH_ERRORS as e:
                    if self._elastic:
                        dead[pos] = e
                        raise _GangResizeNeeded(dead, results, unharvested)
                    raise TrainingWorkerError(
                        f"training worker died: {e}") from e
                except Exception as e:
                    raise TrainingWorkerError(
                        f"training worker died: {e}") from e
                if res.error is not None:
                    if self._elastic and isinstance(res.error, PreemptedError):
                        # the loop checkpointed and bowed out; treat the
                        # rank as departed
                        dead[pos] = res.error
                        raise _GangResizeNeeded(dead, results, unharvested)
                    raise TrainingWorkerError(
                        f"train loop failed on a worker: {res.error!r}"
                    ) from res.error
                results[pos] = res
        if all(r.done for r in results):
            return None
        # Mixed done/not-done means a worker returned early from its loop —
        # the remaining workers would deadlock on their next collective.
        if any(r.done for r in results):
            raise TrainingWorkerError(
                "some workers finished while others are still reporting — "
                "train_loop_per_worker must report the same number of times "
                "on every rank")
        return results

    # ------------------------------------------------------------- resize
    def _resize(self, ev: _GangResizeNeeded):
        """Shrink-and-continue: drop the lost ranks, re-form the gang at
        the new world size, resume from the last consistent checkpoint."""
        t0 = time.monotonic()
        wg = self.worker_group
        old_world = len(wg.workers)
        cause = ev.dead[min(ev.dead)]
        new_world = old_world - len(ev.dead)
        if new_world < self._min_workers:
            raise TrainingWorkerError(
                f"gang lost rank(s) {sorted(ev.dead)} and would shrink to "
                f"{new_world} < min_workers={self._min_workers}: {cause!r}"
            ) from cause
        reason = (f"gang resize: lost rank(s) {sorted(ev.dead)} "
                  f"({type(cause).__name__}), shrinking "
                  f"{old_world} -> {new_world}")
        logger.warning(reason)
        self._restart_gang(dead=set(ev.dead), partial=ev.results,
                           reason=reason, pending_refs=ev.pending_refs)
        self.elastic_stats.append({
            "event": "shrink",
            "old_world": old_world,
            "new_world": len(self.worker_group.workers),
            "cause": type(cause).__name__,
            "resume_s": time.monotonic() - t0,
        })

    def _maybe_grow(self):
        """Grow back toward the target world size when capacity returns.
        One probe per cooldown window: a replacement worker is created in
        a freed placement bundle; if it comes up, the gang restarts at
        the larger world size through the same resize path."""
        wg = self.worker_group
        target = self._target_workers
        if wg.pg is not None:
            # a placement group has exactly num_workers bundles; growth
            # beyond that has nowhere to land
            target = min(target, self.scaling.num_workers)
        if len(wg.workers) >= target:
            return
        now = time.monotonic()
        if now - self._last_resize_t < config.elastic_grow_cooldown_s:
            return
        self._last_resize_t = now
        t0 = time.monotonic()
        old_world = len(wg.workers)
        pos = wg.try_add_worker(config.elastic_grow_probe_timeout_s)
        if pos is None:
            return  # capacity has not returned; try again after cooldown
        reason = (f"gang resize: capacity returned, growing "
                  f"{old_world} -> {old_world + 1}")
        logger.info(reason)
        self._restart_gang(dead=set(), partial=None, reason=reason,
                           fresh={pos})
        self.elastic_stats.append({
            "event": "grow",
            "old_world": old_world,
            "new_world": len(self.worker_group.workers),
            "cause": None,
            "resume_s": time.monotonic() - t0,
        })

    def _restart_gang(self, dead: set, partial, reason: str,
                      fresh: Optional[set] = None,
                      pending_refs: Optional[Dict[int, Any]] = None):
        """Common resize machinery: abort collectives, interrupt + drain
        surviving sessions, drop dead ranks, re-rank, re-wire the
        backend at the new generation, and restart every loop from the
        last consistent checkpoint."""
        assert self._spec is not None, "start_training not called"
        wg = self.worker_group
        dead = set(dead)
        fresh = fresh or set()
        # 1. poison the old collective generation so blocked survivors
        #    fail over in ~one poll interval
        self.backend.abort_collectives(wg, reason)
        # 2. ask surviving sessions to unwind at their next boundary
        survivors = [(pos, w) for pos, w in enumerate(wg.workers)
                     if pos not in dead and pos not in fresh]
        for pos, w in survivors:
            w.interrupt_session.remote(reason)
        # 3. drain each survivor to its done sentinel; one that cannot
        #    unwind within the window is wedged — kill it and treat it
        #    as dead (never below min_workers: checked by callers for
        #    the planned dead set, re-checked here for escalations)
        pending_refs = pending_refs or {}
        for pos, w in survivors:
            if partial is not None and pos < len(partial) \
                    and partial[pos] is not None and partial[pos].done:
                continue  # loop already finished; nothing to drain
            if not self._drain_worker(w, pending_refs.get(pos)):
                logger.warning("worker at position %d failed to drain; "
                               "treating it as dead", pos)
                dead.add(pos)
        # 4. close the drained sessions SYNCHRONOUSLY — end_session must
        #    complete before the start_training below, and with
        #    max_concurrency > 1 actor calls are not ordered
        for pos, w in survivors:
            if pos in dead:
                continue
            try:
                ray_tpu.get(w.end_session.remote())
            except _DEATH_ERRORS:
                dead.add(pos)  # died after draining; demote it too
        new_world = len(wg.workers) - len(dead)
        if new_world < self._min_workers:
            raise TrainingWorkerError(
                f"gang shrank to {new_world} < min_workers="
                f"{self._min_workers} while draining ({reason})")
        # 5. tear down only the lost ranks; bundles stay reserved
        wg.remove_positions(dead)
        # 6. new incarnation: bump generation, compact ranks, re-wire
        wg.generation += 1
        wg.reassign_ranks()
        self.backend.on_resize(wg, self.backend_config)
        # 7. resume every rank from the last consistent checkpoint with
        #    data re-sharded by the new (rank, world_size)
        resume = self._pick_resume_checkpoint()
        spec = self._spec
        n = len(wg.workers)
        shards = spec["shard_fn"](n) if spec["shard_fn"] else None
        storage_info = dict(spec["storage_info"] or {})
        if storage_info:
            storage_info["checkpoint_index_start"] = self._ckpt_index_next
        refs = []
        for rank, w in enumerate(wg.workers):
            refs.append(w.start_training.remote(
                spec["train_fn"], spec["config"], spec["context_kwargs"],
                resume, shards[rank] if shards else None,
                storage_info or None))
        ray_tpu.get(refs)
        self._last_resize_t = time.monotonic()

    def _drain_worker(self, w, first_ref=None) -> bool:
        """Pump a survivor's results until its done sentinel. True when
        it unwound cleanly; False when it was wedged (killed here).

        Calls are strictly serialized, starting from the aborted
        harvest's still-in-flight next_result ref when there is one — a
        second concurrent reader on the same session would steal queue
        items (possibly the done sentinel itself) and strand the drain.
        """
        deadline = time.monotonic() + _DRAIN_TIMEOUT_S
        ref = first_ref if first_ref is not None else w.next_result.remote()
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                res = ray_tpu.get(ref, timeout=remaining)
            except _DEATH_ERRORS:
                return False  # died while draining; caller demotes it
            except GetTimeoutError:
                break
            if res.done:
                return True
            ref = w.next_result.remote()
        try:
            ray_tpu.kill(w)
        # rtpu-lint: disable=L4 — the wedged worker may have died on its
        # own in the window; kill is best-effort and the caller already
        # treats the worker as dead
        except Exception:
            pass
        return False

    def _pick_resume_checkpoint(self) -> Optional[str]:
        """Newest consistent checkpoint: walk the full-batch checkpoints
        newest-first, validating each manifest, and fall back to the
        run's original resume point when none survive."""
        from ray_tpu.train.storage import validate_checkpoint_dir

        while self._consistent_ckpts:
            path = self._consistent_ckpts[-1]
            if validate_checkpoint_dir(path):
                return path
            logger.warning("checkpoint %s is torn/partial; falling back "
                           "to the previous one", path)
            self._consistent_ckpts.pop()
        return self._spec["checkpoint_path"] if self._spec else None

    # --------------------------------------------------------- chaos site
    def _fire_gang_resize(self, key: str):
        """Driver-side gang_resize fault site: after the matching batch
        commits, kill (SIGKILL) or preempt (SIGTERM) the highest-rank
        worker — the deterministic stand-in for a TPU pool preemption."""
        from ray_tpu.core import fault_injection

        if not fault_injection.enabled():
            return
        action = fault_injection.fire("gang_resize", key)
        if action is None:
            return
        wg = self.worker_group
        victim = wg.workers[-1]
        info = ray_tpu.get(victim.node_info.remote())
        sig = signal.SIGKILL if action == "kill" else signal.SIGTERM
        logger.warning("gang_resize fault: sending %s to rank %d (pid %d) "
                       "after batch %s", sig.name, len(wg.workers) - 1,
                       info["pid"], key)
        os.kill(info["pid"], sig)

    def shutdown(self):
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group)
            self.worker_group.shutdown()
            self.worker_group = None
