"""Gang of training worker actors placed in a placement group.

Reference: python/ray/train/_internal/worker_group.py:102 (WorkerGroup) and
backend_executor.py:67. One actor per worker; on real TPU pods each worker is
one host of the slice (multi-controller JAX), gang-placed STRICT_PACK so the
gang shares an ICI domain.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.placement_group import PlacementGroup, placement_group, \
    remove_placement_group
from ray_tpu.core.scheduling_strategies import PlacementGroupSchedulingStrategy
from ray_tpu.train import session as session_mod
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext, TrainingResult, _TrainSession


class _TrainWorker:
    """The actor class hosting one training worker."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.session: Optional[_TrainSession] = None

    # --------------------------------------------------------- bookkeeping
    def node_info(self) -> Dict[str, Any]:
        return {
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
            "rank": self.rank,
        }

    def set_env(self, env: Dict[str, str]):
        os.environ.update(env)

    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary function in the worker process (backend hooks)."""
        return fn(*args, **kwargs)

    # ----------------------------------------------------------- training
    def start_training(self, train_fn: Callable, config: Dict[str, Any],
                       context_kwargs: Dict[str, Any],
                       checkpoint_path: Optional[str],
                       dataset_shards: Optional[Dict[str, Any]] = None,
                       storage_info: Optional[Dict[str, Any]] = None):
        from ray_tpu.train.checkpoint import Checkpoint
        from ray_tpu.train.storage import StorageContext

        ctx = TrainContext(world_rank=self.rank, world_size=self.world_size,
                           local_rank=self.rank, local_world_size=self.world_size,
                           **context_kwargs)
        ckpt = Checkpoint(checkpoint_path) if checkpoint_path else None
        storage = None
        ckpt_start = 0
        if storage_info:
            storage = StorageContext(storage_info["storage_path"],
                                     storage_info["experiment_name"],
                                     storage_info["trial_name"])
            ckpt_start = storage_info.get("checkpoint_index_start", 0)
        self.session = _TrainSession(train_fn, config or {}, ctx,
                                     checkpoint=ckpt,
                                     dataset_shards=dataset_shards,
                                     storage=storage,
                                     checkpoint_index_start=ckpt_start)
        session_mod._set_session(self.session)
        self.session.start()

    def next_result(self) -> TrainingResult:
        assert self.session is not None, "start_training not called"
        return self.session.next_result()

    def end_session(self):
        session_mod._set_session(None)
        self.session = None


class WorkerGroup:
    """Creates and addresses the gang."""

    def __init__(self, scaling: ScalingConfig):
        self.scaling = scaling
        self.pg: Optional[PlacementGroup] = None
        self.workers: List[Any] = []

    def start(self):
        n = self.scaling.num_workers
        bundles = [self.scaling.bundle_for_worker() for _ in range(n)]
        if any(bundles[0].values()):
            self.pg = placement_group(bundles, strategy=self.scaling.placement_strategy)
            if not self.pg.wait(timeout_seconds=60.0):
                pg, self.pg = self.pg, None
                try:
                    remove_placement_group(pg)
                except Exception:
                    pass
                raise RuntimeError(
                    f"placement group for {n} training workers "
                    f"(bundle={bundles[0]}) not ready within 60s — the "
                    f"cluster cannot satisfy the ScalingConfig")
        worker_cls = ray_tpu.remote(_TrainWorker)
        self.workers = []
        for rank in range(n):
            opts: Dict[str, Any] = {"max_restarts": 0}
            if self.pg is not None:
                opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group=self.pg, placement_group_bundle_index=rank)
                opts["num_cpus"] = self.scaling.num_cpus_per_worker
                if self.scaling.use_tpu:
                    opts["resources"] = {"TPU": float(self.scaling.chips_per_worker or 1)}
            self.workers.append(worker_cls.options(**opts).remote(
                rank, n))

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Run fn on every worker, return all results (ordered by rank)."""
        return ray_tpu.get([w.execute.remote(fn, *args, **kwargs)
                            for w in self.workers])

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return ray_tpu.get(self.workers[rank].execute.remote(fn, *args, **kwargs))

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def __len__(self):
        return len(self.workers)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            self.pg = None
