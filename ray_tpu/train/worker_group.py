"""Gang of training worker actors placed in a placement group.

Reference: python/ray/train/_internal/worker_group.py:102 (WorkerGroup) and
backend_executor.py:67. One actor per worker; on real TPU pods each worker is
one host of the slice (multi-controller JAX), gang-placed STRICT_PACK so the
gang shares an ICI domain.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.config import config
from ray_tpu.core.placement_group import PlacementGroup, placement_group, \
    placement_group_table, remove_placement_group
from ray_tpu.core.scheduling_strategies import PlacementGroupSchedulingStrategy
from ray_tpu.exceptions import PlacementGroupError
from ray_tpu.train import session as session_mod
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext, TrainingResult, _TrainSession


class _TrainWorker:
    """The actor class hosting one training worker."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.session: Optional[_TrainSession] = None

    # --------------------------------------------------------- bookkeeping
    def node_info(self) -> Dict[str, Any]:
        return {
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
            "rank": self.rank,
        }

    def set_env(self, env: Dict[str, str]):
        os.environ.update(env)

    def update_rank(self, rank: int, world_size: int):
        """Re-address this worker after an elastic resize (ranks compact
        to 0..new_world-1). Takes effect for the NEXT session; the env
        mirrors what set_env wrote at gang start."""
        self.rank = rank
        self.world_size = world_size
        os.environ["RAY_TPU_RANK"] = str(rank)
        os.environ["RAY_TPU_WORLD_SIZE"] = str(world_size)

    def interrupt_session(self, reason: str) -> bool:
        """Driver-side resize entry point. Runs on a spare concurrency
        slot (the actor is created with max_concurrency > 1) so it can
        overtake a next_result call blocked on the result queue."""
        if self.session is None:
            return False
        self.session.interrupt(reason)
        return True

    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary function in the worker process (backend hooks)."""
        return fn(*args, **kwargs)

    # ----------------------------------------------------------- training
    def start_training(self, train_fn: Callable, config: Dict[str, Any],
                       context_kwargs: Dict[str, Any],
                       checkpoint_path: Optional[str],
                       dataset_shards: Optional[Dict[str, Any]] = None,
                       storage_info: Optional[Dict[str, Any]] = None):
        from ray_tpu.train.checkpoint import Checkpoint
        from ray_tpu.train.storage import StorageContext

        ctx = TrainContext(world_rank=self.rank, world_size=self.world_size,
                           local_rank=self.rank, local_world_size=self.world_size,
                           **context_kwargs)
        ckpt = Checkpoint(checkpoint_path) if checkpoint_path else None
        storage = None
        ckpt_start = 0
        if storage_info:
            storage = StorageContext(storage_info["storage_path"],
                                     storage_info["experiment_name"],
                                     storage_info["trial_name"])
            ckpt_start = storage_info.get("checkpoint_index_start", 0)
        self.session = _TrainSession(train_fn, config or {}, ctx,
                                     checkpoint=ckpt,
                                     dataset_shards=dataset_shards,
                                     storage=storage,
                                     checkpoint_index_start=ckpt_start)
        session_mod._set_session(self.session)
        self.session.start()

    def next_result(self) -> TrainingResult:
        assert self.session is not None, "start_training not called"
        return self.session.next_result()

    def end_session(self):
        session_mod._set_session(None)
        self.session = None


class WorkerGroup:
    """Creates and addresses the gang.

    Elastic bookkeeping: ``bundle_indices[i]`` is the placement-group
    bundle worker ``i`` occupies — on a shrink the dead worker's bundle
    is released by the runtime and stays reserved in the PG, so a later
    grow re-creates a worker into the freed bundle. ``generation``
    counts resizes; the collective layer uses it to name each
    incarnation's coordinator.
    """

    def __init__(self, scaling: ScalingConfig):
        self.scaling = scaling
        self.pg: Optional[PlacementGroup] = None
        self.workers: List[Any] = []
        self.bundle_indices: List[int] = []
        self.generation = 0

    def _worker_options(self, bundle_index: Optional[int]) -> Dict[str, Any]:
        # max_concurrency=4: interrupt_session/node_info must be able to
        # overtake a next_result call blocked on the session queue during
        # an elastic resize. trap_sigterm: maintenance SIGTERMs become
        # the train.preempted() flag, installed on the worker's MAIN
        # thread at actor creation (actor calls run on pool threads,
        # which may not set signal handlers).
        opts: Dict[str, Any] = {"max_restarts": 0, "max_concurrency": 4,
                                "trap_sigterm": True}
        if self.pg is not None and bundle_index is not None:
            opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                placement_group=self.pg,
                placement_group_bundle_index=bundle_index)
            opts["num_cpus"] = self.scaling.num_cpus_per_worker
            if self.scaling.use_tpu:
                opts["resources"] = {"TPU": float(self.scaling.chips_per_worker or 1)}
        return opts

    def _unsatisfiable_detail(self, bundles: List[Dict[str, float]]) -> str:
        """Name the first bundle the cluster cannot currently satisfy."""
        from ray_tpu import state as state_mod

        reason = None
        if self.pg is not None:
            entry = placement_group_table().get(self.pg.id.hex()) or {}
            reason = entry.get("infeasible_reason")
        if reason:
            return reason
        try:
            avail = state_mod.available_resources()
            total = state_mod.cluster_resources()
        except (RuntimeError, KeyError):
            avail = total = {}
        for i, b in enumerate(bundles):
            short = {k: v for k, v in b.items()
                     if v > total.get(k, 0.0)} if total else {}
            if short:
                return (f"bundle {i} {b} exceeds the cluster's total "
                        f"resources (have {total})")
            short = {k: v for k, v in b.items()
                     if v > avail.get(k, 0.0)} if avail else {}
            if short:
                return (f"bundle {i} {b} cannot be satisfied from "
                        f"available resources {avail}")
        return (f"bundle {bundles[0]} x{len(bundles)} "
                f"({self.scaling.placement_strategy}) is not placeable")

    def start(self):
        n = self.scaling.num_workers
        bundles = [self.scaling.bundle_for_worker() for _ in range(n)]
        if any(bundles[0].values()):
            timeout_s = config.train_pg_ready_timeout_s
            self.pg = placement_group(bundles, strategy=self.scaling.placement_strategy)
            if not self.pg.wait(timeout_seconds=timeout_s):
                detail = self._unsatisfiable_detail(bundles)
                pg, self.pg = self.pg, None
                try:
                    remove_placement_group(pg)
                # rtpu-lint: disable=L4 — best-effort teardown of a PG
                # that never became ready; the PlacementGroupError below
                # carries the actual failure
                except Exception:
                    pass
                raise PlacementGroupError(
                    f"placement group for {n} training workers not ready "
                    f"within {timeout_s:g}s (train_pg_ready_timeout_s): "
                    f"{detail}")
        worker_cls = ray_tpu.remote(_TrainWorker)
        self.workers = []
        self.bundle_indices = []
        for rank in range(n):
            idx = rank if self.pg is not None else None
            self.workers.append(
                worker_cls.options(**self._worker_options(idx)).remote(rank, n))
            self.bundle_indices.append(rank)

    # ------------------------------------------------------ elastic resize
    def remove_positions(self, positions) -> None:
        """Drop (already-dead or killed) workers from the gang; their PG
        bundles stay reserved for a later grow."""
        doomed = set(positions)
        for pos in doomed:
            try:
                ray_tpu.kill(self.workers[pos])
            # rtpu-lint: disable=L4 — the worker is usually already dead
            # (that is why it is being removed); kill is best-effort
            except Exception:
                pass
        self.workers = [w for i, w in enumerate(self.workers)
                        if i not in doomed]
        self.bundle_indices = [b for i, b in enumerate(self.bundle_indices)
                               if i not in doomed]

    def try_add_worker(self, probe_timeout_s: float):
        """Grow by one: create a worker in a freed placement bundle and
        probe it. Returns the new worker position, or None when capacity
        has not returned (the probe actor is killed)."""
        from ray_tpu.exceptions import ActorDiedError, ActorUnavailableError, \
            GetTimeoutError

        free = [i for i in range(self.scaling.num_workers)
                if i not in self.bundle_indices]
        if self.pg is not None and not free:
            return None
        idx = free[0] if free else None
        worker_cls = ray_tpu.remote(_TrainWorker)
        w = worker_cls.options(**self._worker_options(idx)).remote(
            len(self.workers), len(self.workers) + 1)
        try:
            ray_tpu.get(w.node_info.remote(), timeout=probe_timeout_s)
        except (GetTimeoutError, ActorDiedError, ActorUnavailableError):
            try:
                ray_tpu.kill(w)
            # rtpu-lint: disable=L4 — probe actor may never have been
            # scheduled; kill is best-effort cleanup
            except Exception:
                pass
            return None
        self.workers.append(w)
        self.bundle_indices.append(idx if idx is not None else len(self.bundle_indices))
        return len(self.workers) - 1

    def reassign_ranks(self) -> None:
        """Compact ranks to 0..len-1 after a resize (rank order is
        preserved for survivors, new workers take the tail)."""
        n = len(self.workers)
        ray_tpu.get([w.update_rank.remote(i, n)
                     for i, w in enumerate(self.workers)])

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Run fn on every worker, return all results (ordered by rank)."""
        return ray_tpu.get([w.execute.remote(fn, *args, **kwargs)
                            for w in self.workers])

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return ray_tpu.get(self.workers[rank].execute.remote(fn, *args, **kwargs))

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def __len__(self):
        return len(self.workers)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            # rtpu-lint: disable=L4 — teardown: workers may already be
            # dead (preempted/killed); nothing to recover
            except Exception:
                pass
        self.workers = []
        self.bundle_indices = []
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            # rtpu-lint: disable=L4 — teardown: the PG may already be
            # removed (failed start path); nothing to recover
            except Exception:
                pass
            self.pg = None
