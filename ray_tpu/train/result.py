"""Result of a training/tuning run (reference: python/ray/air/result.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    path: str = ""
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    # One entry per elastic resize the run rode through: {event:
    # "shrink"|"grow", old_world, new_world, cause, resume_s}.
    elastic_stats: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def metrics_dataframe(self):
        import pandas as pd

        return pd.DataFrame(self.metrics_history)

    def __repr__(self):
        keys = {k: v for k, v in (self.metrics or {}).items()
                if not k.startswith("_")}
        return (f"Result(metrics={keys}, checkpoint={self.checkpoint}, "
                f"error={self.error!r})")
