"""Storage layout for experiments/trials/checkpoints.

Reference: python/ray/train/_internal/storage.py:352 (StorageContext).
Layout: <storage_path>/<experiment_name>/<trial_name>/checkpoint_NNNNNN/.
Local paths use the local fs; remote URIs (s3://, gs://) go through fsspec.
"""

from __future__ import annotations

import datetime
import json
import os
import shutil
import uuid
from typing import Optional

import fsspec

from ray_tpu.train.checkpoint import Checkpoint, _is_local

# Commit marker for crash-safe checkpoint persistence: written LAST into
# the staged checkpoint dir, listing every file and its size. A dir
# without a valid manifest whose sizes match is torn (the persisting
# worker died mid-copy) and resume falls back to the previous tracked
# checkpoint. Checkpoints written by older versions have no manifest and
# are trusted as-is.
MANIFEST_NAME = ".rtpu_ckpt_manifest.json"


def _build_manifest(dirpath: str, index: int) -> dict:
    files = {}
    for base, _, names in os.walk(dirpath):
        for name in names:
            if base == dirpath and name == MANIFEST_NAME:
                continue
            full = os.path.join(base, name)
            rel = os.path.relpath(full, dirpath)
            files[rel] = os.path.getsize(full)
    return {"index": index, "files": files}


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def validate_checkpoint_dir(path: str, fs=None) -> bool:
    """Is a persisted checkpoint dir consistent (fully committed)?

    True for manifest-less dirs (legacy / foreign checkpoints — nothing
    to check against); False when the dir is missing, the manifest is
    unreadable, or any listed file is missing or size-mismatched."""
    if fs is not None and not _is_local(fs):
        try:
            if not fs.exists(path):
                return False
            mpath = path.rstrip("/") + "/" + MANIFEST_NAME
            if not fs.exists(mpath):
                return True
            with fs.open(mpath, "r") as f:
                manifest = json.load(f)
            for rel, size in manifest.get("files", {}).items():
                fpath = path.rstrip("/") + "/" + rel
                if not fs.exists(fpath) or fs.info(fpath).get("size") != size:
                    return False
            return True
        except (OSError, ValueError, KeyError):
            return False
    if not os.path.isdir(path):
        return False
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return True
    try:
        with open(mpath, encoding="utf-8") as f:
            manifest = json.load(f)
        files = manifest.get("files")
        if not isinstance(files, dict):
            return False
        for rel, size in files.items():
            full = os.path.join(path, rel)
            if not os.path.isfile(full) or os.path.getsize(full) != size:
                return False
        return True
    except (OSError, ValueError):
        return False


class StorageContext:
    def __init__(self, storage_path: str, experiment_name: Optional[str] = None,
                 trial_name: Optional[str] = None):
        if "://" in storage_path:
            self.fs, _, paths = fsspec.get_fs_token_paths(storage_path)
            self.root = paths[0] if isinstance(paths, list) else paths
        else:
            self.fs = fsspec.filesystem("file")
            self.root = os.path.abspath(storage_path)
        if experiment_name is None:
            stamp = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
            experiment_name = f"rtpu_experiment_{stamp}"
        self.experiment_name = experiment_name
        self.trial_name = trial_name or f"trial_{uuid.uuid4().hex[:8]}"

    # ------------------------------------------------------------- paths
    @property
    def experiment_path(self) -> str:
        return os.path.join(self.root, self.experiment_name)

    @property
    def trial_path(self) -> str:
        return os.path.join(self.experiment_path, self.trial_name)

    def checkpoint_path(self, index: int) -> str:
        return os.path.join(self.trial_path, f"checkpoint_{index:06d}")

    def ensure_trial_dir(self):
        self.fs.makedirs(self.trial_path, exist_ok=True)

    # --------------------------------------------------------- persisting
    def persist_checkpoint_dir(self, local_dir: str, index: int) -> Checkpoint:
        """Upload/copy a locally-written checkpoint dir into the trial dir.

        Crash-safe on local filesystems: the dir is staged under a
        hidden ``.tmp-*`` sibling, a manifest (file list + sizes) is
        fsynced into it, and the stage is committed with an atomic
        rename + parent-dir fsync — a worker dying mid-persist leaves
        only an invisible stage, never a torn ``checkpoint_NNNNNN``.
        Deterministic elastic replay may re-persist an index that
        already exists (an orphan written past the resume point); the
        replacement wins. On object stores rename isn't atomic; the
        manifest is uploaded last as the commit marker and resume
        validates it."""
        dest = self.checkpoint_path(index)
        if _is_local(self.fs):
            if os.path.abspath(local_dir) == os.path.abspath(dest):
                # written in place: just commit the manifest
                self._write_manifest(dest, index)
                return Checkpoint(dest, self.fs)
            parent = os.path.dirname(dest)
            os.makedirs(parent, exist_ok=True)
            tmp = os.path.join(
                parent, f".tmp-{os.path.basename(dest)}-{uuid.uuid4().hex[:8]}")
            shutil.copytree(local_dir, tmp)
            self._write_manifest(tmp, index)
            if os.path.exists(dest):
                shutil.rmtree(dest)
            os.rename(tmp, dest)
            _fsync_dir(parent)
        else:
            self.fs.put(local_dir.rstrip("/") + "/", dest, recursive=True)
            manifest = {"index": index, "files": {}}
            for base, _, names in os.walk(local_dir):
                for name in names:
                    full = os.path.join(base, name)
                    rel = os.path.relpath(full, local_dir)
                    manifest["files"][rel] = os.path.getsize(full)
            with self.fs.open(
                    dest.rstrip("/") + "/" + MANIFEST_NAME, "w") as f:
                f.write(json.dumps(manifest))
        return Checkpoint(dest, self.fs)

    @staticmethod
    def _write_manifest(dirpath: str, index: int) -> None:
        manifest = _build_manifest(dirpath, index)
        mpath = os.path.join(dirpath, MANIFEST_NAME)
        tmp = mpath + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, mpath)
        _fsync_dir(dirpath)

    def delete_checkpoint(self, checkpoint: Checkpoint):
        try:
            checkpoint.filesystem.rm(checkpoint.path, recursive=True)
        except FileNotFoundError:
            pass

    def for_trial(self, trial_name: str) -> "StorageContext":
        s = StorageContext.__new__(StorageContext)
        s.fs, s.root = self.fs, self.root
        s.experiment_name, s.trial_name = self.experiment_name, trial_name
        return s
