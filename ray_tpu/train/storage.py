"""Storage layout for experiments/trials/checkpoints.

Reference: python/ray/train/_internal/storage.py:352 (StorageContext).
Layout: <storage_path>/<experiment_name>/<trial_name>/checkpoint_NNNNNN/.
Local paths use the local fs; remote URIs (s3://, gs://) go through fsspec.
"""

from __future__ import annotations

import datetime
import os
import shutil
import uuid
from typing import Optional

import fsspec

from ray_tpu.train.checkpoint import Checkpoint, _is_local


class StorageContext:
    def __init__(self, storage_path: str, experiment_name: Optional[str] = None,
                 trial_name: Optional[str] = None):
        if "://" in storage_path:
            self.fs, _, paths = fsspec.get_fs_token_paths(storage_path)
            self.root = paths[0] if isinstance(paths, list) else paths
        else:
            self.fs = fsspec.filesystem("file")
            self.root = os.path.abspath(storage_path)
        if experiment_name is None:
            stamp = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
            experiment_name = f"rtpu_experiment_{stamp}"
        self.experiment_name = experiment_name
        self.trial_name = trial_name or f"trial_{uuid.uuid4().hex[:8]}"

    # ------------------------------------------------------------- paths
    @property
    def experiment_path(self) -> str:
        return os.path.join(self.root, self.experiment_name)

    @property
    def trial_path(self) -> str:
        return os.path.join(self.experiment_path, self.trial_name)

    def checkpoint_path(self, index: int) -> str:
        return os.path.join(self.trial_path, f"checkpoint_{index:06d}")

    def ensure_trial_dir(self):
        self.fs.makedirs(self.trial_path, exist_ok=True)

    # --------------------------------------------------------- persisting
    def persist_checkpoint_dir(self, local_dir: str, index: int) -> Checkpoint:
        """Upload/copy a locally-written checkpoint dir into the trial dir."""
        dest = self.checkpoint_path(index)
        if _is_local(self.fs):
            if os.path.abspath(local_dir) != os.path.abspath(dest):
                os.makedirs(dest, exist_ok=True)
                shutil.copytree(local_dir, dest, dirs_exist_ok=True)
        else:
            self.fs.put(local_dir.rstrip("/") + "/", dest, recursive=True)
        return Checkpoint(dest, self.fs)

    def delete_checkpoint(self, checkpoint: Checkpoint):
        try:
            checkpoint.filesystem.rm(checkpoint.path, recursive=True)
        except FileNotFoundError:
            pass

    def for_trial(self, trial_name: str) -> "StorageContext":
        s = StorageContext.__new__(StorageContext)
        s.fs, s.root = self.fs, self.root
        s.experiment_name, s.trial_name = self.experiment_name, trial_name
        return s
