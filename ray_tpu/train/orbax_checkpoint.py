"""Sharded checkpointing with Orbax — the TPU-native save/restore path.

Reference role: ray.train's framework checkpointing
(python/ray/train/_checkpoint.py + torch's distributed checkpoint); the
TPU-first implementation is Orbax: each process writes ONLY the array
shards it owns (no gather, no single-host memory spike), and restore
reassembles a pytree laid out by a target sharding — possibly a
DIFFERENT mesh than the one that saved it (Orbax reshards on load).
That property is what makes elastic gang restarts cheap: a 4-process
gang's checkpoint restores onto an 8-process mesh unchanged.

Use inside a Train loop::

    from ray_tpu.train import orbax_checkpoint as oc

    oc.save(step_dir, {"params": params, "opt": opt_state})   # all ranks
    state = oc.restore(step_dir, like={"params": params_spec, ...})

``save`` is collective: EVERY process in the jax.distributed job must
call it with its shards. ``restore`` takes a pytree of arrays or
ShapeDtypeStructs carrying shardings and lays the data out accordingly.
"""

from __future__ import annotations

import os
from typing import Any, Optional


def save(path: str, state: Any, *, force: bool = True) -> str:
    """Write ``state`` (a pytree of jax arrays — sharded arrays write
    only the local shards per process). Collective across the
    jax.distributed job. Returns the checkpoint path."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, state, force=force)
    return path


def restore(path: str, like: Optional[Any] = None) -> Any:
    """Read a checkpoint. With ``like`` (a pytree of arrays or
    ShapeDtypeStructs with `.sharding` set), arrays are restored DIRECTLY
    into that layout — including onto a different mesh/process count than
    the one that saved them (Orbax reshards on read)."""
    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        if like is None:
            return ckptr.restore(path)
        restore_args = jax.tree.map(
            lambda x: ocp.ArrayRestoreArgs(
                sharding=getattr(x, "sharding", None),
                dtype=getattr(x, "dtype", None),
            ), like)
        return ckptr.restore(
            path, restore_args=restore_args)
