"""In-training-loop session: report/get_checkpoint/get_context.

Reference: python/ray/train/_internal/session.py (_TrainSession :111,
report :667, get_checkpoint :754). The user loop runs on a thread inside the
worker actor; ``report`` hands a result to the actor thread and blocks in
lockstep until the driver has consumed it — that keeps all workers advancing
step-for-step, which matters on TPU where every mesh member must enter the
same jitted collective program together.
"""

from __future__ import annotations

import os
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

_session: Optional["_TrainSession"] = None
_session_lock = threading.Lock()


@dataclass
class TrainingResult:
    metrics: Dict[str, Any]
    checkpoint_dir: Optional[str] = None   # worker-local dir to persist
    done: bool = False
    error: Optional[BaseException] = None


@dataclass
class TrainContext:
    """What a worker knows about its place in the gang (reference:
    ray.train.get_context() → TrainContext)."""

    world_size: int = 1
    world_rank: int = 0
    local_rank: int = 0
    local_world_size: int = 1
    node_rank: int = 0
    trial_name: str = ""
    experiment_name: str = ""
    trial_dir: str = ""
    metadata: Dict[str, Any] = field(default_factory=dict)

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_trial_name(self) -> str:
        return self.trial_name

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_trial_dir(self) -> str:
        return self.trial_dir


class SessionInterruptedError(BaseException):
    """Raised inside the user train loop when the driver interrupts the
    session (gang resize: a peer died or the gang is growing back). A
    BaseException on purpose: a user loop's ``except Exception`` must not
    swallow the interrupt — the loop is being unwound so the worker can
    rejoin at the new world size and resume from the last consistent
    checkpoint."""


class _TrainSession:
    """Pumps results from the user training thread to the actor thread.

    Checkpoint persistence happens HERE, worker-side, inside ``report`` —
    before the result is handed to the driver — because the worker-local
    checkpoint dir may be temporary and, on multi-node, not reachable from
    the driver at all (reference: storage upload in train/_internal/
    session.py report path).
    """

    def __init__(self, train_fn, config: Dict[str, Any], context: TrainContext,
                 checkpoint: Optional[Checkpoint] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 storage=None, checkpoint_index_start: int = 0,
                 checkpoint_upload_rank: Optional[int] = 0):
        self.context = context
        self.loaded_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        self.storage = storage
        self._ckpt_index = checkpoint_index_start
        self._ckpt_upload_rank = checkpoint_upload_rank
        self._result_q: "queue.Queue[TrainingResult]" = queue.Queue(maxsize=1)
        self._consumed = threading.Semaphore(0)
        self._finished = False
        self._interrupted: Optional[str] = None

        def runner():
            try:
                train_fn(config) if _wants_config(train_fn) else train_fn()
                self._result_q.put(TrainingResult(metrics={}, done=True))
            except BaseException as e:  # surfaced to the driver, not swallowed
                # Includes SessionInterruptedError: the queue may still
                # hold the result the interrupt overtook, but the driver
                # drains every queued result until it sees this done
                # sentinel, so the blocking put always completes — and
                # the sentinel is never dropped.
                self._result_q.put(
                    TrainingResult(metrics={}, done=True, error=e))

        self._thread = threading.Thread(target=runner, daemon=True,
                                        name="rtpu-train-loop")

    def start(self):
        self._thread.start()

    # --------------------------------------------- called by the driver
    # (via _TrainWorker.interrupt_session, on the actor's second
    # concurrency slot while next_result may be blocked on the first)
    def interrupt(self, reason: str = "gang resize"):
        """Ask the train loop to unwind at its next report boundary.

        Protocol: set the flag, then release one ``_consumed`` token so a
        loop blocked in lockstep (report() waiting for the driver) wakes
        up and observes the flag. A loop blocked inside a collective is
        unblocked separately by the coordinator abort. The driver must
        keep calling ``next_result`` (draining) until it sees a ``done``
        result — in-flight reports complete normally before the loop
        raises SessionInterruptedError."""
        self._interrupted = reason
        self._consumed.release()

    # ------------------------------------------------- called by train_fn
    def report(self, metrics: Dict[str, Any],
               checkpoint_dir: Optional[str] = None):
        if self._interrupted is not None:
            raise SessionInterruptedError(self._interrupted)
        persisted = None
        if checkpoint_dir is not None:
            if (self.storage is not None
                    and (self._ckpt_upload_rank is None
                         or self.context.world_rank == self._ckpt_upload_rank)):
                ckpt = self.storage.persist_checkpoint_dir(
                    checkpoint_dir, self._ckpt_index)
                persisted = ckpt.path
            self._ckpt_index += 1
        self._result_q.put(TrainingResult(metrics=dict(metrics),
                                          checkpoint_dir=persisted))
        # Lockstep: wait until the driver consumed this result before the
        # training loop continues (mirrors reference's blocking report).
        self._consumed.acquire()
        if self._interrupted is not None:
            raise SessionInterruptedError(self._interrupted)

    # --------------------------------------------------- called by driver
    def next_result(self, timeout: Optional[float] = None) -> TrainingResult:
        res = self._result_q.get(timeout=timeout)
        if res.done:
            self._finished = True
        else:
            self._consumed.release()
        return res

    def finished(self) -> bool:
        return self._finished


# ------------------------------------------------------------ public API

def _get_session() -> _TrainSession:
    if _session is None:
        raise RuntimeError(
            "No train session active — this API must be called inside a "
            "train_loop_per_worker launched by a Trainer.")
    return _session


def _set_session(s: Optional[_TrainSession]):
    global _session
    _session = s


# ---- preemption (TPU maintenance events arrive as SIGTERM) -----------------

_preempt_event = threading.Event()


class PreemptedError(RuntimeError):
    """Raised by a train loop that observed preemption (after saving its
    checkpoint). The trainer treats it as a gang-restart signal that does
    NOT consume the failure budget — preemptions are scheduled events,
    not faults (reference analogue: spot/maintenance handling in
    cluster autoscaling; TPU docs deliver maintenance events as SIGTERM
    with a grace window)."""


def _core_preempt_event():
    """The worker-process-level preemption flag, when running inside a
    runtime worker (set by the SIGTERM handler that trap_sigterm actors
    install at creation — see core/worker_proc.py). None driver-side."""
    from ray_tpu.core import runtime_context

    core = runtime_context.get_core_or_none()
    return getattr(core, "preempted", None)


def preempted() -> bool:
    """True once a preemption signal (SIGTERM) reached this worker.
    Poll at step boundaries: save a checkpoint, then raise
    PreemptedError so the gang restarts cleanly on fresh resources."""
    if _preempt_event.is_set():
        return True
    ev = _core_preempt_event()
    return ev is not None and ev.is_set()


def _flag_preemption():
    """Mark this worker preempted (what the SIGTERM handler does; also
    the hook for environments that deliver maintenance events through a
    channel other than signals)."""
    _preempt_event.set()


def _install_preemption_handler():
    """Worker-side: arm the SIGTERM→flag route for a (new or resized)
    gang incarnation. The actual signal handler lives in the worker
    process's main thread, installed at actor creation for trap_sigterm
    actors (core/worker_proc.py) — actor calls run on pool threads when
    max_concurrency > 1 and may not set signal handlers themselves, so
    this call only CLEARS stale flags: a preemption observed by a
    previous gang on a reused process must not re-fire, while a SIGTERM
    landing after this point must stick. In-process sessions (driver-
    side unit tests) get a best-effort direct install instead."""
    import signal

    _preempt_event.clear()
    ev = _core_preempt_event()
    if ev is not None:
        ev.clear()
    # Only the main thread may install handlers (CPython rule). On a
    # pool thread the process-level handler installed at actor creation
    # (core/worker_proc.py) owns the SIGTERM route — skip explicitly
    # rather than swallow the ValueError, which is how the original
    # never-armed bug stayed invisible.
    if threading.current_thread() is threading.main_thread():
        signal.signal(signal.SIGTERM, lambda signum, frame:
                      _flag_preemption())


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None,
           *, checkpoint_dir: Optional[str] = None):
    """Report metrics (and optionally a just-written checkpoint dir) to the
    driver. Blocks until the driver has processed the result."""
    s = _get_session()
    if checkpoint is not None and checkpoint_dir is None:
        checkpoint_dir = checkpoint.path
    s.report(metrics, checkpoint_dir=checkpoint_dir)


def get_checkpoint() -> Optional[Checkpoint]:
    """The latest persisted checkpoint to resume from (None on fresh start)."""
    return _get_session().loaded_checkpoint


def get_context() -> TrainContext:
    s = _session
    return s.context if s is not None else TrainContext()


def get_dataset_shard(name: str = "train"):
    """This worker's shard of a dataset passed to the Trainer
    (reference: ray.train.get_dataset_shard)."""
    return _get_session().dataset_shards.get(name)


def _wants_config(fn) -> bool:
    import inspect

    try:
        return len(inspect.signature(fn).parameters) >= 1
    except (TypeError, ValueError):
        return False
