"""Predictors: checkpoint -> batch inference over Data (reference:
python/ray/train/predictor.py + the batch-inference-on-Data pattern that
replaced BatchPredictor).

A Predictor wraps a loaded model; ``predict_batches`` maps it over a
Dataset with an actor pool so the model loads once per worker (the
TPU-side model stays resident in the actor)."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np


class Predictor:
    """Subclass: implement from_checkpoint() and predict(batch)->batch."""

    @classmethod
    def from_checkpoint(cls, checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Predictor over a jitted apply function + params pytree."""

    def __init__(self, params, apply_fn: Callable,
                 input_column: str = "data",
                 output_column: str = "predictions"):
        import jax

        self._params = params
        self._apply = jax.jit(apply_fn)
        self._in = input_column
        self._out = output_column

    @classmethod
    def from_checkpoint(cls, checkpoint, apply_fn: Callable,
                        load_params: Optional[Callable] = None,
                        **kwargs) -> "JaxPredictor":
        """load_params(dir_path) -> params; defaults to a pickle named
        params.pkl in the checkpoint directory."""
        import os
        import pickle

        path = checkpoint.path if hasattr(checkpoint, "path") else checkpoint
        if load_params is not None:
            params = load_params(path)
        else:
            with open(os.path.join(path, "params.pkl"), "rb") as f:
                params = pickle.load(f)
        return cls(params, apply_fn, **kwargs)

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        import jax.numpy as jnp

        out = self._apply(self._params, jnp.asarray(batch[self._in]))
        return {**batch, self._out: np.asarray(out)}


def predict_batches(dataset, predictor_cls, *, batch_size: int = 256,
                    concurrency: int = 1, predictor_kwargs: dict = None):
    """Map a Predictor over a Dataset with an actor pool (model loads once
    per pool worker). Returns a new Dataset with predictions."""
    kwargs = predictor_kwargs or {}

    class _PredictUDF:
        def __init__(self):
            self._p = predictor_cls.from_checkpoint(**kwargs) \
                if "checkpoint" in kwargs else predictor_cls(**kwargs)

        def __call__(self, batch):
            return self._p.predict(batch)

    return dataset.map_batches(_PredictUDF, batch_size=batch_size,
                               concurrency=concurrency)
