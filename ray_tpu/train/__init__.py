"""ray_tpu.train: distributed training orchestration over the TPU runtime.

Public surface mirrors ray.train: configs, Checkpoint, report/get_checkpoint/
get_context/get_dataset_shard, DataParallelTrainer/JaxTrainer, Result.
"""

from ray_tpu.train.backend import Backend, BackendConfig, JaxConfig  # noqa: F401
from ray_tpu.train.backend_executor import (  # noqa: F401
    BackendExecutor,
    TrainingWorkerError,
)
from ray_tpu.train.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.train.predictor import (  # noqa: F401
    JaxPredictor,
    Predictor,
    predict_batches,
)
from ray_tpu.train.checkpoint_manager import CheckpointManager  # noqa: F401
from ray_tpu.train.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.data_parallel_trainer import (  # noqa: F401
    DataParallelTrainer,
    JaxTrainer,
)
from ray_tpu.train.result import Result  # noqa: F401
from ray_tpu.train.session import (  # noqa: F401
    PreemptedError,
    SessionInterruptedError,
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    preempted,
    report,
)
from ray_tpu.train.storage import (  # noqa: F401
    StorageContext,
    validate_checkpoint_dir,
)
from ray_tpu.train.worker_group import WorkerGroup  # noqa: F401

__all__ = [
    "Backend", "BackendConfig", "JaxConfig",
    "BackendExecutor", "TrainingWorkerError",
    "Checkpoint", "CheckpointManager", "CheckpointConfig",
    "FailureConfig", "RunConfig", "ScalingConfig",
    "DataParallelTrainer", "JaxTrainer", "Result",
    "PreemptedError", "preempted", "SessionInterruptedError",
    "TrainContext", "get_checkpoint", "get_context", "get_dataset_shard",
    "report", "StorageContext", "validate_checkpoint_dir", "WorkerGroup",
]
