"""Train/AIR config dataclasses.

Reference surface: python/ray/air/config.py (ScalingConfig, RunConfig,
FailureConfig, CheckpointConfig). TPU-first difference: ScalingConfig speaks
the slice/host/chip topology (chips per worker, optional topology string)
instead of GPU counts.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    """How many training workers, and what each one holds.

    num_workers: size of the worker gang (one actor per worker; on real TPU
        pods this is one worker per host, multi-controller JAX style).
    use_tpu: reserve TPU chips for each worker.
    chips_per_worker: TPU chips each worker owns (maps to the "TPU" resource).
    resources_per_worker: extra custom resources per worker.
    placement_strategy: bundle placement (PACK/SPREAD/STRICT_PACK/STRICT_SPREAD);
        STRICT_PACK keeps the gang on one ICI domain.
    topology: optional TPU topology hint, e.g. "v5e-8" — lets the scheduler
        gang-place onto a whole sub-slice.
    min_workers / max_workers: set min_workers to make the gang ELASTIC —
        a worker death becomes a resize event (shrink and continue from
        the last consistent checkpoint) instead of a gang failure, as long
        as at least min_workers survive; the gang grows back toward
        min(num_workers, max_workers) when capacity returns. Leave
        min_workers unset for the classic all-or-nothing gang.
    """

    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: Optional[int] = None
    num_cpus_per_worker: float = 1.0
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None
    min_workers: Optional[int] = None
    max_workers: Optional[int] = None

    def __post_init__(self):
        if self.min_workers is not None:
            if not 1 <= self.min_workers <= self.num_workers:
                raise ValueError(
                    f"min_workers={self.min_workers} must be in "
                    f"[1, num_workers={self.num_workers}]")
        if self.max_workers is not None and self.max_workers < self.num_workers:
            raise ValueError(
                f"max_workers={self.max_workers} must be >= "
                f"num_workers={self.num_workers}")

    @property
    def elastic(self) -> bool:
        return self.min_workers is not None

    def bundle_for_worker(self) -> Dict[str, float]:
        b: Dict[str, float] = {}
        if self.num_cpus_per_worker:
            b["CPU"] = float(self.num_cpus_per_worker)
        if self.use_tpu:
            b["TPU"] = float(self.chips_per_worker or 1)
        for k, v in (self.resources_per_worker or {}).items():
            b[k] = float(v)
        return b

    @property
    def total_chips(self) -> int:
        if not self.use_tpu:
            return 0
        return int(self.chips_per_worker or 1) * self.num_workers


@dataclass
class FailureConfig:
    """Gang fault tolerance: restart the whole worker group from the last
    checkpoint up to ``max_failures`` times (reference: air/config.py
    FailureConfig; executor restart in train/_internal/backend_executor.py)."""

    max_failures: int = 0
    # Preemptions (PreemptedError after a SIGTERM maintenance event) are
    # scheduled, not faults: they restart the gang WITHOUT consuming
    # max_failures, bounded by this cap so a mis-signalled fleet cannot
    # restart-loop forever.
    max_preemptions: int = 16


@dataclass
class CheckpointConfig:
    """Top-k checkpoint retention (reference: air/config.py CheckpointConfig,
    enforced by train/_internal/checkpoint_manager.py)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass
class RunConfig:
    """Experiment-level config (reference: air/config.py RunConfig)."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1
    log_to_file: bool = False

    def resolved_storage_path(self) -> str:
        if self.storage_path:
            return self.storage_path
        return os.environ.get(
            "RAY_TPU_STORAGE_PATH",
            os.path.join(os.path.expanduser("~"), "ray_tpu_results"),
        )
