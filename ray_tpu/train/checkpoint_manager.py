"""Top-k checkpoint retention (reference:
python/ray/train/_internal/checkpoint_manager.py)."""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import CheckpointConfig
from ray_tpu.train.storage import StorageContext, validate_checkpoint_dir

logger = logging.getLogger(__name__)


@dataclass
class _TrackedCheckpoint:
    checkpoint: Checkpoint
    index: int
    metrics: Dict[str, Any]

    def score(self, attr: str):
        return self.metrics.get(attr)


class CheckpointManager:
    def __init__(self, storage: StorageContext, config: CheckpointConfig):
        self.storage = storage
        self.config = config
        self.checkpoints: List[_TrackedCheckpoint] = []
        self._next_index = 0

    def register(self, local_dir: str, metrics: Dict[str, Any]) -> Checkpoint:
        """Persist a worker-written checkpoint dir and apply retention."""
        idx = self._next_index
        ckpt = self.storage.persist_checkpoint_dir(local_dir, idx)
        return self._track(ckpt, idx, metrics)

    def register_persisted(self, path: str, metrics: Dict[str, Any]) -> Checkpoint:
        """Track a checkpoint a worker already uploaded to storage."""
        return self._track(Checkpoint(path, self.storage.fs),
                           self._next_index, metrics)

    def _track(self, ckpt: Checkpoint, idx: int,
               metrics: Dict[str, Any]) -> Checkpoint:
        self._next_index = idx + 1
        self.checkpoints.append(_TrackedCheckpoint(ckpt, idx, dict(metrics)))
        self._enforce_retention()
        return ckpt

    @property
    def next_index(self) -> int:
        return self._next_index

    @property
    def latest(self) -> Optional[Checkpoint]:
        return self.checkpoints[-1].checkpoint if self.checkpoints else None

    def latest_consistent(self) -> Optional[Checkpoint]:
        """The newest tracked checkpoint that passes manifest validation.

        Torn/partial dirs (the persisting worker died mid-commit, or the
        dir was damaged after the fact) are dropped from tracking with a
        warning and the walk continues to the previous checkpoint —
        resume never crashes on a bad dir, it just loses fewer-than-all
        steps."""
        while self.checkpoints:
            tc = self.checkpoints[-1]
            if validate_checkpoint_dir(tc.checkpoint.path,
                                       tc.checkpoint.filesystem):
                return tc.checkpoint
            logger.warning(
                "checkpoint %s is torn/partial (manifest validation "
                "failed); falling back to the previous checkpoint",
                tc.checkpoint.path)
            self.checkpoints.pop()
        return None

    @property
    def best(self) -> Optional[Checkpoint]:
        attr = self.config.checkpoint_score_attribute
        if not self.checkpoints:
            return None
        if not attr:
            return self.latest
        scored = [c for c in self.checkpoints if c.score(attr) is not None]
        if not scored:
            return self.latest
        key = lambda c: c.score(attr)  # noqa: E731
        pick = max if self.config.checkpoint_score_order == "max" else min
        return pick(scored, key=key).checkpoint

    def _enforce_retention(self):
        k = self.config.num_to_keep
        if k is None or len(self.checkpoints) <= k:
            return
        attr = self.config.checkpoint_score_attribute
        # Never delete the most recent checkpoint (it's the resume point).
        candidates = self.checkpoints[:-1]
        if attr:
            order_max = self.config.checkpoint_score_order == "max"
            candidates = sorted(
                candidates,
                key=lambda c: (c.score(attr) is not None,
                               c.score(attr) if c.score(attr) is not None else 0),
                reverse=order_max,
            )
        n_delete = len(self.checkpoints) - k
        doomed = candidates[-n_delete:]
        for tc in doomed:
            self.storage.delete_checkpoint(tc.checkpoint)
            self.checkpoints.remove(tc)
