"""Autoscaler: demand-driven node scale-up/down over a NodeProvider.

Reference: autoscaler v1's monitor loop + provider interface
(python/ray/autoscaler/_private/monitor.py:126, node_provider.py) and
v2's instance-manager split. The monitor polls the GCS cluster view plus
per-node state (queued tasks): sustained queueing with no headroom
launches a node; sustained idleness above min_nodes terminates one. The
provider abstracts WHERE nodes come from — the built-in subprocess
provider launches node-server processes on this host (the fixture/test
path); a TPU-pod provider would request slices instead.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu.core.cluster.rpc import ClientCache, RpcClient, RpcError


class NodeProvider:
    """Interface: launch/terminate cluster nodes."""

    def launch_node(self) -> None:
        raise NotImplementedError

    def terminate_node(self, address: Tuple[str, int]) -> None:
        raise NotImplementedError


class SubprocessNodeProvider(NodeProvider):
    """Launches node-server subprocesses on this host (the local
    deployment mode; reference analogue: local/node_provider.py)."""

    def __init__(self, gcs_address: Tuple[str, int], num_workers: int = 2,
                 object_store_memory: int = 128 << 20):
        self._gcs_address = gcs_address
        self._nw = num_workers
        self._mem = object_store_memory
        self.procs: List = []

    def launch_node(self) -> None:
        import subprocess
        import sys

        self.procs.append(subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.cluster.node_server",
             "--gcs", f"{self._gcs_address[0]}:{self._gcs_address[1]}",
             "--num-workers", str(self._nw),
             "--object-store-memory", str(self._mem)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))

    def non_terminated_nodes(self) -> List:
        """Provider ("cloud") view for the v2 reconciler: launched
        subprocesses still running."""
        return [p for p in self.procs if p.poll() is None]

    def terminate_node(self, address: Tuple[str, int]) -> None:
        # ask the node to drain and exit; its process follows
        try:
            from ray_tpu.core.cluster.rpc import cluster_authkey

            RpcClient(address, cluster_authkey(), connect_timeout=2.0
                      ).call(("shutdown_node",))
        except (RpcError, Exception):  # noqa: BLE001
            pass


class GceTpuNodeProvider(NodeProvider):
    """Provision TPU-VM slices as cluster nodes through the Cloud TPU
    REST API (reference: autoscaler/_private/gcp/node_provider.py + its
    tpu.py — same role, REST-direct instead of the google client lib,
    which this image does not ship).

    Every HTTP call goes through an injectable
    ``transport(method, url, body) -> dict`` so (a) tests drive the full
    request flow against a mocked API — exactly how the reference tests
    its AWS provider (python/ray/tests/aws/) — and (b) real deployments
    plug in an authed session (metadata-server token on GCE, or a
    service-account wrapper). The default transport uses urllib with the
    GCE metadata server and raises an actionable error off-GCE.

    Launched nodes boot with a startup script that joins the cluster:
    ``ray_tpu start --address <gcs>`` with the cluster authkey in the
    environment; terminate deletes the TPU node whose network endpoint
    matches the cluster address being removed.
    """

    API = "https://tpu.googleapis.com/v2"

    def __init__(self, project: str, zone: str,
                 gcs_address: Tuple[str, int],
                 accelerator_type: str = "v5litepod-8",
                 runtime_version: str = "tpu-ubuntu2204-base",
                 name_prefix: str = "rtpu-node",
                 authkey_hex: Optional[str] = None,
                 transport=None):
        self._parent = f"projects/{project}/locations/{zone}"
        self._gcs = tuple(gcs_address)
        # GCE label values: lowercase letters/digits/underscore/dash ONLY
        self._cluster_label = (f"{self._gcs[0]}-{self._gcs[1]}"
                               .replace(".", "-").replace(":", "-").lower())
        self._accel = accelerator_type
        self._runtime = runtime_version
        self._prefix = name_prefix
        self._authkey_hex = authkey_hex or ""
        self._transport = transport or self._default_transport
        self._counter = 0

    # -- transport ----------------------------------------------------------

    def _default_transport(self, method: str, url: str, body=None) -> dict:
        import json as _json
        import urllib.request

        token_req = urllib.request.Request(
            "http://metadata.google.internal/computeMetadata/v1/instance/"
            "service-accounts/default/token",
            headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(token_req, timeout=5) as r:
                token = _json.loads(r.read())["access_token"]
        except Exception as e:  # noqa: BLE001
            raise RuntimeError(
                "GceTpuNodeProvider needs GCE metadata-server credentials "
                "(run on a GCE VM with a TPU-scoped service account) or an "
                "injected transport") from e
        req = urllib.request.Request(
            url, method=method,
            data=None if body is None else _json.dumps(body).encode(),
            headers={"Authorization": f"Bearer {token}",
                     "Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return _json.loads(r.read() or b"{}")

    # -- provider interface -------------------------------------------------

    def _startup_script(self) -> str:
        host, port = self._gcs
        return (f"#!/bin/bash\n"
                f"export RTPU_CLUSTER_AUTHKEY={self._authkey_hex}\n"
                f"python -m ray_tpu start --address {host}:{port}\n")

    def launch_node(self) -> None:
        self._counter += 1
        name = f"{self._prefix}-{self._counter}"
        body = {
            "acceleratorType": self._accel,
            "runtimeVersion": self._runtime,
            "labels": {"rtpu-cluster": self._cluster_label},
            "metadata": {"startup-script": self._startup_script()},
        }
        self._transport(
            "POST", f"{self.API}/{self._parent}/nodes?nodeId={name}", body)

    def non_terminated_nodes(self) -> List[dict]:
        out = self._transport("GET", f"{self.API}/{self._parent}/nodes")
        return [n for n in out.get("nodes", [])
                if n.get("labels", {}).get("rtpu-cluster")
                == self._cluster_label
                and n.get("state") not in ("DELETING", "TERMINATED")]

    def terminate_node(self, address: Tuple[str, int]) -> None:
        host = address[0]
        for n in self.non_terminated_nodes():
            eps = n.get("networkEndpoints") or []
            if any(e.get("ipAddress") == host for e in eps):
                self._transport("DELETE", f"{self.API}/{n['name']}", None)
                return


class AutoscalerMonitor:
    """The control loop (reference: monitor.py:126 StandardAutoscaler)."""

    def __init__(self, gcs_address: Tuple[str, int], provider: NodeProvider,
                 min_nodes: int = 1, max_nodes: int = 4,
                 scale_up_after_ticks: int = 3,
                 scale_down_after_ticks: int = 20,
                 tick_s: float = 0.5,
                 authkey: Optional[bytes] = None):
        from ray_tpu.core.cluster.rpc import cluster_authkey

        self._authkey = authkey or cluster_authkey()
        self._gcs = RpcClient(tuple(gcs_address), self._authkey)
        self._nodes = ClientCache(self._authkey)
        self._provider = provider
        self._min = min_nodes
        self._max = max_nodes
        self._up_after = scale_up_after_ticks
        self._down_after = scale_down_after_ticks
        self._tick_s = tick_s
        self._busy_ticks = 0
        self._idle_ticks: Dict[Tuple[str, int], int] = {}
        self._launching_until = 0.0
        self.events: List[dict] = []
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    # ------------------------------------------------------------------ loop

    def _loop(self):
        while not self._stop:
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — the monitor must survive
                pass
            time.sleep(self._tick_s)

    def _tick(self):
        view = self._gcs.call(("list_nodes", True))
        nodes = view["nodes"]
        n = len(nodes)

        queued = 0
        per_node_busy: Dict[Tuple[str, int], bool] = {}
        for node in nodes:
            addr = tuple(node["address"])
            try:
                s = self._nodes.get(addr).call(("state",))
            except RpcError:
                continue
            q = s["tasks"]["queued"]
            running = s["tasks"]["running"]
            active_actors = sum(1 for a in s["actors"]
                                if a["state"] != "DEAD")
            # demand = explicit queue + tasks batched beyond the worker
            # slots (the dispatcher pipelines onto workers, so a saturated
            # node can show an empty queue with a deep inflight backlog)
            slots = max(1, len(s["workers"]))
            queued += q + max(0, running - slots)
            per_node_busy[addr] = bool(q or running or active_actors)

        # ---- scale up: sustained queueing and room to grow
        if queued > 0 and n < self._max:
            self._busy_ticks += 1
        else:
            self._busy_ticks = 0
        if (self._busy_ticks >= self._up_after
                and time.monotonic() >= self._launching_until):
            self._provider.launch_node()
            self._launching_until = time.monotonic() + 15.0
            self._busy_ticks = 0
            self.events.append({"action": "launch", "queued": queued,
                                "nodes": n, "ts": time.time()})

        # ---- scale down: a node idle long enough, above the floor
        for addr, busy in per_node_busy.items():
            self._idle_ticks[addr] = (0 if busy
                                      else self._idle_ticks.get(addr, 0) + 1)
        if n > self._min:
            victim = next(
                (a for a, t in sorted(self._idle_ticks.items(),
                                      key=lambda kv: -kv[1])
                 if t >= self._down_after and a in per_node_busy),
                None)
            if victim is not None:
                self._provider.terminate_node(victim)
                self._idle_ticks.pop(victim, None)
                self.events.append({"action": "terminate",
                                    "address": list(victim),
                                    "ts": time.time()})

    def stop(self):
        self._stop = True
        self._gcs.close()
        self._nodes.close_all()
