"""Ray-Client-style proxy: many remote clients, one shared cluster.

Reference: python/ray/util/client/server/proxier.py — the client server
accepts thin clients on a single public endpoint and gives EACH ONE an
isolated driver (`SpecificServer` per client there; a per-tenant
``ClusterCore`` here), so tenants get separate ownership domains:
object refs, actors, and lineage created by one client are owned by
that client's core, and a disconnect (explicit or by idle timeout)
tears down exactly that tenant's state through the normal owner-death
cleanup — never another client's.

Wire model: the client ships the SAME core-client calls a local driver
makes (register_function / submit_task / create_actor / get_objects /
...), cloudpickled. ObjectRefs cross the boundary by id: the pickle
resolver rebinds them to whichever core deserializes them — the
tenant's ClusterCore on the proxy, the ``ProxyCore`` on the client —
so nested refs in args and returned refs both work unchanged.

Usage::

    # on a machine with cluster connectivity
    srv = ClientProxyServer(gcs_address)

    # anywhere that can reach the proxy
    ray_tpu.init(address=f"ray://{srv.address[0]}:{srv.address[1]}")
"""

from __future__ import annotations

import pickle
import threading
import time
import uuid
from typing import Any, Dict, Optional, Tuple

from ray_tpu.core import runtime_context
from ray_tpu.core.cluster.rpc import RpcClient, RpcServer, cluster_authkey
from ray_tpu.core.ids import NodeID, WorkerID

# the core-client surface a tenant may invoke (everything api.py and the
# remote-function/actor layers call on a driver core)
_ALLOWED_OPS = frozenset({
    "register_function", "submit_task", "create_actor",
    "submit_actor_task", "put_object", "get_objects", "wait",
    "kill_actor", "cancel_task", "free_objects", "get_named_actor",
    "get_actor_method_opts", "prepare_runtime_env",
})


class ClientProxyServer:
    """Multi-tenant proxy (reference: proxier.py:113 ProxyManager)."""

    def __init__(self, gcs_address: Tuple[str, int],
                 host: str = "127.0.0.1", port: int = 0,
                 authkey: Optional[bytes] = None,
                 idle_timeout_s: float = 60.0):
        self._gcs = tuple(gcs_address)
        self._authkey = authkey or cluster_authkey()
        self._idle_timeout_s = idle_timeout_s
        # _lock guards the tenant table (cheap ops only — heartbeats and
        # the reaper must never wait behind a slow tenant call);
        # _ctx_lock guards the brief runtime_context swaps around
        # pickling, where ObjectRefs rebind via the process-global
        # context. The core calls themselves run under NEITHER lock, so
        # tenants block and fetch concurrently.
        self._lock = threading.RLock()
        self._ctx_lock = threading.Lock()
        self._tenants: Dict[str, dict] = {}
        self._stop = False
        self._server = RpcServer(self._handle, self._authkey, host, port)
        self.address = self._server.address
        threading.Thread(target=self._reaper, daemon=True,
                         name="client-proxy-reaper").start()

    # ------------------------------------------------------------- handlers

    def _handle(self, msg: Any, ctx: dict) -> Any:
        op = msg[0]
        if op == "client_connect":
            return self._connect()
        if op == "client_touch":
            with self._lock:
                t = self._tenants.get(msg[1])
                if t is None:
                    raise KeyError(f"unknown client {msg[1]!r}")
                t["last"] = time.monotonic()
            return True
        if op == "client_disconnect":
            self._disconnect(msg[1])
            return True
        if op == "client_op":
            _, client_id, method, payload = msg
            return self._tenant_op(client_id, method, payload)
        raise ValueError(f"unknown proxy op {op!r}")

    def _connect(self) -> str:
        from ray_tpu.core.cluster.cluster_core import ClusterCore

        client_id = uuid.uuid4().hex[:12]
        with self._lock:
            prev = runtime_context.get_core_or_none()
            try:
                runtime_context.set_core(None)
                core = ClusterCore(self._gcs, authkey=self._authkey)
            finally:
                runtime_context.set_core(prev)
            self._tenants[client_id] = {"core": core,
                                        "last": time.monotonic()}
        return client_id

    def _disconnect(self, client_id: str):
        with self._lock:
            t = self._tenants.pop(client_id, None)
        if t is not None:
            try:
                t["core"].shutdown()
            except Exception:  # noqa: BLE001 — tenant teardown best-effort
                pass

    def _tenant_op(self, client_id: str, method: str,
                   payload: bytes) -> bytes:
        if method not in _ALLOWED_OPS:
            raise ValueError(f"op {method!r} not allowed through the proxy")
        import cloudpickle

        with self._lock:
            t = self._tenants.get(client_id)
            if t is None:
                raise KeyError(f"unknown client {client_id!r}")
            t["last"] = time.monotonic()
            core = t["core"]
        with self._ctx_lock:
            prev = runtime_context.get_core_or_none()
            runtime_context.set_core(core)  # refs rebind to this tenant
            try:
                args, kwargs = pickle.loads(payload)
            finally:
                runtime_context.set_core(prev)
        result = getattr(core, method)(*args, **kwargs)
        with self._lock:
            t2 = self._tenants.get(client_id)
            if t2 is not None:  # a long get must not look idle
                t2["last"] = time.monotonic()
        with self._ctx_lock:
            prev = runtime_context.get_core_or_none()
            runtime_context.set_core(core)
            try:
                return cloudpickle.dumps(result)
            finally:
                runtime_context.set_core(prev)

    def _reaper(self):
        while not self._stop:
            time.sleep(min(5.0, self._idle_timeout_s / 4))
            cutoff = time.monotonic() - self._idle_timeout_s
            with self._lock:
                dead = [cid for cid, t in self._tenants.items()
                        if t["last"] < cutoff]
            for cid in dead:
                self._disconnect(cid)

    @property
    def num_tenants(self) -> int:
        with self._lock:
            return len(self._tenants)

    def close(self):
        self._stop = True
        with self._lock:
            cids = list(self._tenants)
        for cid in cids:
            self._disconnect(cid)
        self._server.close()


class ProxyCore:
    """Client-side core: the same duck-typed surface a local driver core
    exposes, each call forwarded to this client's tenant on the proxy
    (reference: util/client/worker.py Worker). Installed by
    ``ray_tpu.init(address="ray://host:port")``."""

    is_client = True

    def __init__(self, address: Tuple[str, int],
                 authkey: Optional[bytes] = None,
                 heartbeat_s: float = 10.0):
        self._rpc = RpcClient(tuple(address), authkey or cluster_authkey())
        self._client_id = self._rpc.call(("client_connect",))
        self.node_id = NodeID.from_random()
        self.worker_id = WorkerID.from_random()
        self._closed = False
        self._hb_s = heartbeat_s
        threading.Thread(target=self._heartbeat, daemon=True,
                         name="proxy-core-hb").start()

    def _heartbeat(self):
        while not self._closed:
            time.sleep(self._hb_s)
            try:
                self._rpc.call(("client_touch", self._client_id))
            except Exception:  # noqa: BLE001 — next get/put will surface
                pass

    def _op(self, method: str, *args, **kwargs):
        import cloudpickle

        payload = cloudpickle.dumps((args, kwargs))
        out = self._rpc.call(
            ("client_op", self._client_id, method, payload))
        return pickle.loads(out)

    def __getattr__(self, name: str):
        if name.startswith("_") or name not in _ALLOWED_OPS:
            raise AttributeError(name)
        return lambda *a, **kw: self._op(name, *a, **kw)

    def shutdown(self):
        if not self._closed:
            self._closed = True
            try:
                self._rpc.call(("client_disconnect", self._client_id))
            except Exception:  # noqa: BLE001
                pass
            self._rpc.close()
