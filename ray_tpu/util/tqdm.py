"""Distributed progress bars multiplexed on the driver terminal.

Reference: python/ray/experimental/tqdm_ray.py — remote tasks/actors
construct a ``tqdm``-shaped bar whose state updates travel to the driver,
where a single manager owns the terminal and redraws every live bar as one
block, so bars from concurrent tasks never interleave mid-line.

Transport here is the GCS pubsub plane (cluster mode) or its single-node
mirror (``Runtime.pubsub_op``): each bar publishes compact state dicts on
the ``tqdm`` channel (rate-limited, forced on open/close) and the driver's
:class:`_BarManager` long-polls the channel from seq 0, so bars created
before the manager attached are replayed, not lost. Stdlib only — no
dependency on the real tqdm.

Usage (mirrors tqdm's core surface)::

    from ray_tpu.util import tqdm as tqdm_ray

    @ray_tpu.remote
    def work(n):
        for _ in tqdm_ray.tqdm(range(n), desc="shard"):
            ...

    tqdm_ray.instance()          # driver: attach the multiplexer
    ray_tpu.get([work.remote(100) for _ in range(4)])
"""

from __future__ import annotations

import os
import sys
import threading
import time
import uuid
from typing import Any, Dict, Optional, TextIO, Tuple

CHANNEL = "tqdm"
_BAR_WIDTH = 20
_PUBLISH_INTERVAL_S = 0.05   # per-bar update rate limit on the wire
_RENDER_INTERVAL_S = 0.05    # terminal redraw rate limit


def _core_or_none():
    from ray_tpu.core import runtime_context

    return runtime_context.get_core_or_none()


def _in_worker(core) -> bool:
    return core is not None and type(core).__module__.endswith("worker_proc")


class tqdm:  # noqa: N801 — mirrors the tqdm API
    """Remote-friendly progress bar: state changes publish to the driver
    instead of writing to this process's stderr."""

    def __init__(self, iterable=None, desc: Optional[str] = None,
                 total: Optional[int] = None, position: Optional[int] = None,
                 unit: str = "it", **_ignored):
        if total is None and iterable is not None:
            try:
                total = len(iterable)
            except TypeError:
                total = None
        self._iterable = iterable
        self._uuid = uuid.uuid4().hex
        self._desc = desc or ""
        self._total = total
        self._unit = unit
        self._pos = position
        self._x = 0
        self._closed = False
        self._t0 = time.monotonic()
        self._last_pub = 0.0
        self._publish(force=True)

    # -- tqdm surface --------------------------------------------------------

    def update(self, n: int = 1):
        self._x += n
        self._publish()

    def set_description(self, desc: str, refresh: bool = True):
        self._desc = desc
        if refresh:
            self._publish(force=True)

    def refresh(self):
        self._publish(force=True)

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._publish(force=True)

    def __iter__(self):
        if self._iterable is None:
            raise TypeError("bar created without an iterable")
        try:
            for item in self._iterable:
                yield item
                self.update(1)
        finally:
            self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- wiring --------------------------------------------------------------

    def _state(self) -> Dict[str, Any]:
        return {
            "uuid": self._uuid, "pid": os.getpid(), "desc": self._desc,
            "total": self._total, "x": self._x, "unit": self._unit,
            "pos": self._pos, "closed": self._closed,
            "elapsed": time.monotonic() - self._t0,
        }

    def _publish(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_pub < _PUBLISH_INTERVAL_S:
            return
        self._last_pub = now
        core = _core_or_none()
        state = self._state()
        if _in_worker(core):
            try:
                core.pubsub_op("publish", CHANNEL, state)
            except Exception:  # noqa: BLE001 — a lost tick, not a crash
                pass
        else:
            # driver-side bar: feed the manager directly, no round trip
            instance().update_bar(state)


def _format_bar(s: Dict[str, Any]) -> str:
    desc = s["desc"] or f"pid={s['pid']}"
    x, total = s["x"], s["total"]
    elapsed = max(s.get("elapsed", 0.0), 1e-9)
    rate = x / elapsed
    if total:
        frac = min(max(x / total, 0.0), 1.0)
        filled = int(frac * _BAR_WIDTH)
        bar = "#" * filled + "-" * (_BAR_WIDTH - filled)
        body = f"|{bar}| {x}/{total} [{frac * 100:3.0f}%]"
    else:
        body = f"{x}{s['unit']}"
    tail = " done" if s["closed"] else ""
    return f"{desc}: {body} {rate:.1f}{s['unit']}/s{tail}"


class _BarManager:
    """Driver-side multiplexer: owns the terminal, one redraw per tick.

    Every render rewrites the whole block of live bars in a single
    ``write()`` under one lock (cursor-up + clear-line per bar), which is
    what prevents interleaving corruption when many tasks publish at
    once — per-bar writes from multiple threads can tear mid-line, one
    block write cannot."""

    def __init__(self, sink: Optional[TextIO] = None):
        self._sink = sink
        self._lock = threading.Lock()
        # (pid, uuid) -> state; insertion order fixes on-screen order
        self._bars: Dict[Tuple[int, str], Dict[str, Any]] = {}
        self._lines_drawn = 0
        self._last_render = 0.0
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # -- ingest --------------------------------------------------------------

    def update_bar(self, state: Dict[str, Any]):
        with self._lock:
            self._bars[(state["pid"], state["uuid"])] = state
            self._render_locked(force=state["closed"])

    def _poll_loop(self):
        since = 0
        while not self._stop:
            core = _core_or_none()
            if core is None or _in_worker(core):
                time.sleep(0.2)
                continue
            try:
                msgs = core.pubsub_op("poll", CHANNEL, since, 0.5)
            except Exception:  # noqa: BLE001 — shutdown / transient rpc
                time.sleep(0.5)
                continue
            for seq, state in msgs:
                since = max(since, seq)
                self.update_bar(state)

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(
                target=self._poll_loop, daemon=True, name="rtpu-tqdm")
            self._thread.start()
        return self

    def stop(self):
        self._stop = True

    # -- render --------------------------------------------------------------

    def _render_locked(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_render < _RENDER_INTERVAL_S:
            return
        self._last_render = now
        sink = self._sink if self._sink is not None else sys.stderr
        lines = [_format_bar(s) for s in self._bars.values()]
        chunk = []
        if self._lines_drawn:
            chunk.append(f"\x1b[{self._lines_drawn}A")
        for ln in lines:
            chunk.append("\r\x1b[2K" + ln + "\n")
        if self._lines_drawn > len(lines):
            chunk.append("\x1b[0J")  # fewer bars than before: clear rest
        try:
            sink.write("".join(chunk))
            sink.flush()
        except (OSError, ValueError):
            return  # sink closed (interpreter teardown)
        self._lines_drawn = len(lines)

    def flush(self):
        with self._lock:
            self._render_locked(force=True)


_instance: Optional[_BarManager] = None
_instance_lock = threading.Lock()


def instance(sink: Optional[TextIO] = None) -> _BarManager:
    """The process-wide bar manager; on the driver this also starts the
    pubsub subscriber thread that mirrors remote bars to the terminal."""
    global _instance
    with _instance_lock:
        if _instance is None:
            _instance = _BarManager(sink=sink)
        elif sink is not None:
            _instance._sink = sink
        if not _in_worker(_core_or_none()):
            _instance.start()
        return _instance


def safe_print(*args, **kwargs):
    """Print without tearing the bar block: temporarily drops below the
    drawn bars (reference: tqdm_ray.safe_print)."""
    mgr = _instance
    if mgr is None:
        print(*args, **kwargs)
        return
    with mgr._lock:
        sink = mgr._sink if mgr._sink is not None else sys.stderr
        if mgr._lines_drawn:
            try:
                sink.write("\r\x1b[2K")
            except (OSError, ValueError):
                pass
        print(*args, **kwargs)
        mgr._lines_drawn = 0
        mgr._render_locked(force=True)
