"""Utility for pipelining work across a fixed pool of actors.

API parity with the reference's ray.util.ActorPool
(python/ray/util/actor_pool.py): submit/map/map_unordered over a set of
actor handles, with get_next / get_next_unordered consumption and dynamic
push/pop of actors.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, TYPE_CHECKING

import ray_tpu

if TYPE_CHECKING:
    from ray_tpu.core.actor import ActorHandle


class ActorPool:
    def __init__(self, actors: Iterable["ActorHandle"]):
        self._idle_actors: List[Any] = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0      # submission order
        self._next_return_index = 0    # ordered-consumption cursor
        self._pending_submits: List[tuple] = []

    def map(self, fn: Callable, values: Iterable) -> Iterator:
        """Apply fn(actor, value) over values; yields results in order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable) -> Iterator:
        """Like map, but yields results as they complete."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def submit(self, fn: Callable, value: Any):
        """Schedule fn(actor, value) on the next idle actor (queued if none)."""
        if self._idle_actors:
            actor = self._idle_actors.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def get_next(self, timeout: float = None) -> Any:
        """Return the next result in submission order."""
        if not self.has_next():
            raise StopIteration("no more results to get")
        i = self._next_return_index
        while i not in self._index_to_future:
            # The producing submit is still queued behind busy actors.
            self._drain_one(timeout)
        future = self._index_to_future[i]
        ready, _ = ray_tpu.wait([future], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next timed out")
        del self._index_to_future[i]
        self._next_return_index += 1
        value = ray_tpu.get(future)
        self._return_actor_for(future)
        return value

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Return the next result to complete, in completion order."""
        if not self.has_next():
            raise StopIteration("no more results to get")
        while not self._future_to_actor:
            self._drain_one(timeout)
        ready, _ = ray_tpu.wait(list(self._future_to_actor), num_returns=1,
                                timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        future = ready[0]
        i, _ = self._future_to_actor[future]
        del self._index_to_future[i]
        value = ray_tpu.get(future)
        self._return_actor_for(future)
        return value

    def _drain_one(self, timeout: float = None):
        """Wait for one in-flight call to finish so a queued submit can run."""
        if not self._future_to_actor:
            raise RuntimeError("pool has queued submits but no idle actors "
                               "and no in-flight calls (no actors in pool?)")
        ready, _ = ray_tpu.wait(list(self._future_to_actor), num_returns=1,
                                timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for an actor to free up")
        # Freeing the actor triggers the next queued submit.
        _, actor = self._future_to_actor.pop(ready[0])
        self._actor_idle(actor)

    def _return_actor_for(self, future):
        entry = self._future_to_actor.pop(future, None)
        if entry is not None:
            self._actor_idle(entry[1])

    def _actor_idle(self, actor):
        self._idle_actors.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def push(self, actor):
        """Add an actor to the pool."""
        busy = {a for _, a in self._future_to_actor.values()}
        if actor in self._idle_actors or actor in busy:
            raise ValueError("actor already belongs to this pool")
        self._actor_idle(actor)

    def pop_idle(self):
        """Remove and return an idle actor, or None if all are busy."""
        if self._idle_actors:
            return self._idle_actors.pop()
        return None

    def has_free(self) -> bool:
        return bool(self._idle_actors) and not self._pending_submits
