"""multiprocessing.Pool shim over the runtime (reference:
python/ray/util/multiprocessing/pool.py — the drop-in Pool that turns
``pool.map(f, xs)`` into distributed tasks).

Only the commonly-used surface: map/imap/imap_unordered/starmap/
apply/apply_async/map_async, with chunking. Initializers run once per
pool actor.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


@ray_tpu.remote
class _PoolWorker:
    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_chunk(self, fn, chunk: List[tuple]) -> List[Any]:
        return [fn(*args) for args in chunk]


class AsyncResult:
    def __init__(self, refs: List[Any], unpack_single: bool):
        self._refs = refs
        self._single = unpack_single

    def get(self, timeout: Optional[float] = None):
        outs = ray_tpu.get(self._refs, timeout=timeout)
        flat = [x for chunk in outs for x in chunk]
        return flat[0] if self._single else flat

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def wait(self, timeout: Optional[float] = None):
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)


class Pool:
    """Drop-in-ish multiprocessing.Pool running on pool actors."""

    def __init__(self, processes: Optional[int] = None, initializer=None,
                 initargs=()):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._n = processes or 4
        self._actors = [_PoolWorker.remote(initializer, initargs)
                        for _ in range(self._n)]
        self._rr = 0
        self._closed = False
        self._outstanding: List[Any] = []
        self._cb_queue = None  # lazy shared callback-drainer thread

    # -- helpers -------------------------------------------------------------

    def _chunks(self, iterable: Iterable, chunksize: Optional[int],
                star: bool) -> List[List[tuple]]:
        items = [tuple(x) if star else (x,) for x in iterable]
        if chunksize is None:
            chunksize = max(1, len(items) // (self._n * 4) or 1)
        return [items[i: i + chunksize]
                for i in range(0, len(items), chunksize)]

    def _submit(self, fn, chunks: List[List[tuple]]) -> List[Any]:
        self._prune_outstanding()
        refs = []
        for chunk in chunks:
            actor = self._actors[self._rr % self._n]
            self._rr += 1
            refs.append(actor.run_chunk.remote(fn, chunk))
        self._outstanding.extend(refs)
        return refs

    def _prune_outstanding(self):
        """Drop completed refs so a long-lived pool doesn't pin every
        past result in the object store (join() only needs pending)."""
        if self._outstanding:
            _, pending = ray_tpu.wait(self._outstanding,
                                      num_returns=len(self._outstanding),
                                      timeout=0)
            self._outstanding = pending

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool is closed")

    # -- API -----------------------------------------------------------------

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        self._check_open()
        return AsyncResult(
            self._submit(fn, self._chunks(iterable, chunksize, star=False)),
            unpack_single=False)

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        self._check_open()
        refs = self._submit(fn, self._chunks(iterable, chunksize, star=True))
        return AsyncResult(refs, unpack_single=False).get()

    def apply(self, fn: Callable, args: tuple = (), kwds: dict = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args=(), kwds=None, callback=None,
                    error_callback=None) -> AsyncResult:
        self._check_open()
        kwds = kwds or {}
        actor = self._actors[self._rr % self._n]
        self._rr += 1
        wrapped = (lambda *a: fn(*a, **kwds)) if kwds else fn
        self._prune_outstanding()
        refs = [actor.run_chunk.remote(wrapped, [tuple(args)])]
        self._outstanding.extend(refs)  # close()+join() must drain these
        res = AsyncResult(refs, unpack_single=True)
        if callback is not None or error_callback is not None:
            # stdlib parity: completion callbacks fire off-thread on ONE
            # shared result-handler thread (like stdlib Pool's
            # _handle_results), not a thread per AsyncResult — a large
            # joblib Parallel(n_jobs=N) run would otherwise hold one
            # live watcher thread per in-flight task
            self._callback_drainer().put((res, callback, error_callback))
        return res

    def _callback_drainer(self):
        if self._cb_queue is None:
            import queue as _q
            import threading

            self._cb_queue = _q.Queue()
            q = self._cb_queue  # capture: terminate() nulls the attr

            def fire(res, cb, ecb):
                try:
                    val = res.get()
                except Exception as e:  # noqa: BLE001
                    if ecb is not None:
                        try:
                            ecb(e)
                        except Exception:  # noqa: BLE001
                            pass
                    return
                if cb is not None:
                    try:
                        cb(val)
                    except Exception:  # noqa: BLE001
                        pass

            def drain():
                # COMPLETION-order dispatch (stdlib _handle_results
                # semantics): poll readiness across all watched results
                # instead of blocking on the oldest — a slow task must
                # not head-of-line block a fast task's callback (which
                # may even be what unblocks the slow one).
                entries: list = []
                while True:
                    try:
                        item = q.get(timeout=0.05 if entries else None)
                    except _q.Empty:
                        item = False  # poll round
                    if item is None:
                        # shutdown sentinel: a final blocking sweep so
                        # close()+join() never loses a callback parked
                        # between polls (join already awaited the refs;
                        # after terminate the gets raise into the error
                        # callbacks)
                        for ent in entries:
                            fire(*ent)
                        return
                    if item is not False:
                        entries.append(item)
                        continue
                    still = []
                    for ent in entries:
                        if ent[0].ready():
                            fire(*ent)
                        else:
                            still.append(ent)
                    entries = still

            threading.Thread(target=drain, daemon=True,
                             name="rtpu-pool-callbacks").start()
        return self._cb_queue

    def imap(self, fn, iterable, chunksize: Optional[int] = 1):
        self._check_open()
        refs = self._submit(fn, self._chunks(iterable, chunksize,
                                             star=False))
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn, iterable, chunksize: Optional[int] = 1):
        self._check_open()
        refs = self._submit(fn, self._chunks(iterable, chunksize,
                                             star=False))
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            for r in ready:
                yield from ray_tpu.get(r)

    def close(self):
        self._closed = True

    def terminate(self):
        self.close()
        if self._cb_queue is not None:
            self._cb_queue.put(None)  # stop the callback drainer
            self._cb_queue = None
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
        self._actors = []

    def join(self):
        """Drain all in-flight work, then tear down the pool actors
        (stdlib contract: close()+join() == orderly shutdown)."""
        if not self._closed:
            raise ValueError("join() before close()")
        if self._outstanding:
            ray_tpu.wait(self._outstanding,
                         num_returns=len(self._outstanding), timeout=None)
            self._outstanding = []
        self.terminate()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
