"""Runtime lock-order sanitizer: ``DebugLock``/``DebugRLock`` and the
lock factory the core routes its ``threading.Lock()`` construction
through.

rtpu-lint L5 proves lock discipline *statically* (bounded-depth,
per-module); this module is the dynamic half — the analogue of the
reference runtime instrumenting its concurrency substrate
(``instrumented_io_context``) instead of auditing call sites by eye.
Armed via ``RTPU_SANITIZE=1`` (read at import; tests flip it with
:func:`arm`/:func:`disarm`), the factory hands out wrapped locks that

- record the **global acquisition-order graph**: an edge A -> B is
  added whenever a thread acquires B while holding A. Acquiring an
  edge that closes a cycle (the classic ABBA inversion — some thread
  ordered A before B, this one orders B before A) raises
  :class:`LockOrderError` at the *second* acquisition site, before the
  thread can actually deadlock;
- raise :class:`LockOrderError` on a same-thread re-acquisition of a
  non-reentrant ``DebugLock`` (guaranteed self-deadlock — the PR 5
  ``_enqueue`` shape, where a dep-ready callback fired under the
  runtime lock re-entered ``_queue_ready``);
- police **fire-outside-lock helpers**: call sites that dispatch
  foreign callables (stored callbacks, resolvers) declare themselves
  with :func:`check_fire_outside`; when armed, dispatching while this
  thread holds any tracked lock raises immediately instead of
  deadlocking whenever the callback happens to need that lock;
- keep per-lock hold-time stats and print a **held-longest report** to
  stderr at process exit (``atexit``), so a hang bisected under the
  sanitizer also names the locks worth staring at.

Disarmed (the default), :func:`make_lock`/:func:`make_rlock` return
plain ``threading`` primitives — zero overhead on hot paths; arming is
a one-flag swap because the core never calls ``threading.Lock()``
directly. Locks constructed *before* arming stay plain; arm first
(env var, or :func:`arm` before building the runtime).

This module is deliberately pure-stdlib with no ray_tpu imports: it
must be importable from the deepest core modules without cycles.
"""

from __future__ import annotations

import atexit
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple, Union

__all__ = [
    "DebugLock", "DebugRLock", "LockOrderError", "arm", "disarm",
    "armed", "make_lock", "make_rlock", "make_condition",
    "check_fire_outside",
    "held_locks", "reset", "hold_stats", "report",
]


class LockOrderError(RuntimeError):
    """A lock acquisition that would (or could) deadlock: same-thread
    re-acquisition of a non-reentrant lock, an acquisition-order cycle
    between named locks, or a callback dispatched through a declared
    fire-outside-lock site while a tracked lock is held."""


_armed = os.environ.get("RTPU_SANITIZE", "") not in ("", "0")

# --- global state, guarded by one plain meta-lock (never a DebugLock) ----
_meta = threading.Lock()
# lock-order edges: name_a -> {name_b: (thread_name, site)} meaning some
# thread acquired b while holding a
_edges: Dict[str, Dict[str, Tuple[str, str]]] = {}
# per-lock hold stats: name -> [count, total_s, max_s, max_site]
_stats: Dict[str, list] = {}

_tls = threading.local()


def _held_stack() -> List["_Held"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class _Held:
    __slots__ = ("lock", "t0", "site")

    def __init__(self, lock, site):
        self.lock = lock
        self.t0 = time.monotonic()
        self.site = site


def armed() -> bool:
    return _armed


def arm() -> None:
    """Arm the sanitizer for locks constructed from now on."""
    global _armed
    _armed = True


def disarm() -> None:
    global _armed
    _armed = False


def reset() -> None:
    """Drop the recorded order graph and hold stats (test isolation)."""
    with _meta:
        _edges.clear()
        _stats.clear()


def held_locks() -> List[str]:
    """Names of tracked locks held by the calling thread, outermost
    first."""
    return [h.lock.name for h in _held_stack()]


def _call_site() -> str:
    """File:line of the nearest caller outside this module (so a
    ``with lock:`` reports the with-statement, not ``__enter__``)."""
    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:
        return "?"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def _path_exists(src: str, dst: str) -> Optional[List[str]]:
    """A recorded path src -> ... -> dst in the order graph, or None.
    Caller holds ``_meta``."""
    seen = {src}
    stack = [[src]]
    while stack:
        path = stack.pop()
        for nxt in _edges.get(path[-1], ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append(path + [nxt])
    return None


def _before_acquire(lock: "DebugLock", reentrant: bool) -> None:
    """Order/self-deadlock checks; runs BEFORE blocking on the inner
    lock so the offending thread raises instead of deadlocking."""
    stack = _held_stack()
    if any(h.lock is lock for h in stack):
        if reentrant:
            return  # RLock re-entry: no new edges
        raise LockOrderError(
            f"self-deadlock: thread {threading.current_thread().name!r} "
            f"re-acquired non-reentrant lock {lock.name!r} it already "
            f"holds (held since {stack[-1].site}); use an RLock or move "
            f"the inner acquisition outside the critical section")
    if not stack:
        return
    site = _call_site()
    me = threading.current_thread().name
    with _meta:
        for h in stack:
            a, b = h.lock.name, lock.name
            if a == b:
                continue
            back = _path_exists(b, a)
            if back is not None:
                owner, where = _edges[back[0]][back[1]]
                raise LockOrderError(
                    f"lock-order inversion: thread {me!r} acquires "
                    f"{b!r} at {site} while holding {a!r} (since "
                    f"{h.site}), but the established order is "
                    f"{' -> '.join(back)} (edge recorded by thread "
                    f"{owner!r} at {where}); an interleaving of the two "
                    f"threads deadlocks")
            _edges.setdefault(a, {}).setdefault(b, (me, site))


def _after_acquire(lock: "DebugLock") -> None:
    _held_stack().append(_Held(lock, _call_site()))


def _on_release(lock: "DebugLock") -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i].lock is lock:
            h = stack.pop(i)
            dt = time.monotonic() - h.t0
            with _meta:
                s = _stats.setdefault(lock.name, [0, 0.0, 0.0, ""])
                s[0] += 1
                s[1] += dt
                if dt > s[2]:
                    s[2], s[3] = dt, h.site
            return


class DebugLock:
    """Order-tracked non-reentrant lock (``threading.Lock`` surface)."""

    _reentrant = False

    def __init__(self, name: str):
        self.name = name
        self._lock = self._make_inner()

    @staticmethod
    def _make_inner():
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _before_acquire(self, self._reentrant)
        got = self._lock.acquire(blocking, timeout)
        if got:
            _after_acquire(self)
        return got

    def release(self) -> None:
        _on_release(self)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def _is_owned(self) -> bool:
        # duck-typed by threading.Condition: our held-stack answers this
        # without the acquire(0) probe (which would distort the graph)
        return any(h.lock is self for h in _held_stack())

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        state = "locked" if self._lock.locked() else "unlocked"
        return f"<{type(self).__name__} {self.name!r} {state}>"


class DebugRLock(DebugLock):
    """Order-tracked reentrant lock (``threading.RLock`` surface,
    including the ``Condition`` save/restore hooks)."""

    _reentrant = True

    @staticmethod
    def _make_inner():
        return threading.RLock()

    def locked(self) -> bool:
        return self._is_owned() or not self._lock.acquire(blocking=False) \
            or (self._lock.release() or False)

    # Condition.wait() on an RLock releases ALL recursion levels via
    # these hooks; mirror the held-stack so a thread parked in wait()
    # is not considered a holder.
    def _release_save(self):
        _on_release(self)
        return self._lock._release_save()

    def _acquire_restore(self, state):
        _before_acquire(self, reentrant=True)
        self._lock._acquire_restore(state)
        _after_acquire(self)


LockLike = Union[threading.Lock, DebugLock]


def make_lock(name: str) -> LockLike:
    """A ``threading.Lock`` — wrapped for order tracking when the
    sanitizer is armed. ``name`` is the stable identity in the global
    order graph (convention: ``Class.attr`` or ``module.global``)."""
    return DebugLock(name) if _armed else threading.Lock()


def make_rlock(name: str):
    return DebugRLock(name) if _armed else threading.RLock()


def make_condition(name: str) -> threading.Condition:
    """A ``threading.Condition`` over a factory lock (reentrant, like
    the bare ``Condition()`` default). ``Condition.wait`` releases the
    lock through the RLock save/restore hooks, so a thread parked in
    ``wait()`` is correctly not a holder in the order graph."""
    return threading.Condition(make_rlock(name))


def check_fire_outside(site: str) -> None:
    """Declare "this statement dispatches foreign callables and must run
    with no tracked lock held". No-op disarmed; armed, raises
    :class:`LockOrderError` when the calling thread holds any tracked
    lock — the PR 5 class of bug (callback fired under the runtime
    lock re-enters the runtime) caught at dispatch time, every time,
    not only on the interleaving that deadlocks."""
    if not _armed:
        return
    stack = _held_stack()
    if stack:
        held = ", ".join(
            f"{h.lock.name!r} (since {h.site})" for h in stack)
        raise LockOrderError(
            f"callback dispatch at fire-outside-lock site {site!r} "
            f"while holding {held}: a callback that needs any of these "
            f"locks deadlocks the holder — move the dispatch outside "
            f"the critical section")


def hold_stats() -> Dict[str, dict]:
    """Per-lock hold statistics recorded so far."""
    with _meta:
        return {name: {"count": s[0], "total_s": s[1], "max_s": s[2],
                       "max_site": s[3]}
                for name, s in _stats.items()}


def report(limit: int = 8, file=None) -> None:
    """Print the held-longest report (top ``limit`` locks by max single
    hold)."""
    stats = hold_stats()
    if not stats:
        return
    file = file or sys.stderr
    rows = sorted(stats.items(), key=lambda kv: -kv[1]["max_s"])[:limit]
    print(f"[rtpu-sanitize] lock hold report, pid {os.getpid()} "
          f"(longest single hold first):", file=file)
    for name, s in rows:
        print(f"[rtpu-sanitize]   {name:<40} max {s['max_s'] * 1e3:8.2f} ms"
              f" at {s['max_site'] or '?':<24} "
              f"({s['count']} holds, {s['total_s'] * 1e3:.2f} ms total)",
              file=file)


@atexit.register
def _exit_report():
    if _armed:
        report()
