"""ray_tpu.util: placement groups, scheduling strategies, collectives
(API parity with the reference's ray.util namespace)."""

from ray_tpu.core.placement_group import (  # noqa: F401
    PlacementGroup,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.core.scheduling_strategies import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)
from ray_tpu.util.actor_pool import ActorPool  # noqa: F401
from ray_tpu.util.queue import Empty, Full, Queue  # noqa: F401
