"""ray_tpu.util: placement groups, scheduling strategies, collectives
(API parity with the reference's ray.util namespace)."""

from ray_tpu.core.placement_group import (  # noqa: F401
    PlacementGroup,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.core.scheduling_strategies import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)
from ray_tpu.util.actor_pool import ActorPool  # noqa: F401
from ray_tpu.util.queue import Empty, Full, Queue  # noqa: F401


def host_node_pid() -> int:
    """Pid of the node-server (or embedded-runtime driver) process that
    hosts this worker. Workers are spawned either directly (cold spawn)
    or by the node's fork zygote; this walks past any ``--zygote``
    ancestor so callers get a stable "which node am I on" answer
    (reference role: ray.get_runtime_context().get_node_id, but by
    process identity, which tests can match against fixture pids)."""
    import os

    pid = os.getppid()
    for _ in range(4):
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode(errors="replace")
            if "--zygote" not in cmd:
                return pid
            with open(f"/proc/{pid}/stat") as f:
                pid = int(f.read().rsplit(")", 1)[1].split()[1])
        except (OSError, ValueError, IndexError):
            return pid
    return pid
