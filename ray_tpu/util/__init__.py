"""ray_tpu.util: placement groups, scheduling strategies, collectives
(API parity with the reference's ray.util namespace).

Re-exports resolve lazily (PEP 562): deep core modules import
``ray_tpu.util.debug_lock`` (the lock factory) at their own import
time, which executes this package ``__init__`` — eager re-imports of
``ray_tpu.core.*`` here would close an import cycle through
``ray_tpu.exceptions``.
"""

from typing import TYPE_CHECKING

_EXPORTS = {
    "PlacementGroup": ("ray_tpu.core.placement_group", "PlacementGroup"),
    "placement_group": ("ray_tpu.core.placement_group", "placement_group"),
    "placement_group_table": ("ray_tpu.core.placement_group",
                              "placement_group_table"),
    "remove_placement_group": ("ray_tpu.core.placement_group",
                               "remove_placement_group"),
    "NodeAffinitySchedulingStrategy": (
        "ray_tpu.core.scheduling_strategies",
        "NodeAffinitySchedulingStrategy"),
    "PlacementGroupSchedulingStrategy": (
        "ray_tpu.core.scheduling_strategies",
        "PlacementGroupSchedulingStrategy"),
    "ActorPool": ("ray_tpu.util.actor_pool", "ActorPool"),
    "Empty": ("ray_tpu.util.queue", "Empty"),
    "Full": ("ray_tpu.util.queue", "Full"),
    "Queue": ("ray_tpu.util.queue", "Queue"),
}

__all__ = sorted(_EXPORTS) + ["host_node_pid"]

if TYPE_CHECKING:  # pragma: no cover — static analyzers only
    from ray_tpu.core.placement_group import (  # noqa: F401
        PlacementGroup,
        placement_group,
        placement_group_table,
        remove_placement_group,
    )
    from ray_tpu.core.scheduling_strategies import (  # noqa: F401
        NodeAffinitySchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )
    from ray_tpu.util.actor_pool import ActorPool  # noqa: F401
    from ray_tpu.util.queue import Empty, Full, Queue  # noqa: F401


def __getattr__(name: str):
    entry = _EXPORTS.get(name)
    if entry is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib

    value = getattr(importlib.import_module(entry[0]), entry[1])
    globals()[name] = value  # cache: resolve once
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))


def host_node_pid() -> int:
    """Pid of the node-server (or embedded-runtime driver) process that
    hosts this worker. Workers are spawned either directly (cold spawn)
    or by the node's fork zygote; this walks past any ``--zygote``
    ancestor so callers get a stable "which node am I on" answer
    (reference role: ray.get_runtime_context().get_node_id, but by
    process identity, which tests can match against fixture pids)."""
    import os

    pid = os.getppid()
    for _ in range(4):
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode(errors="replace")
            if "--zygote" not in cmd:
                return pid
            with open(f"/proc/{pid}/stat") as f:
                pid = int(f.read().rsplit(")", 1)[1].split()[1])
        except (OSError, ValueError, IndexError):
            return pid
    return pid
