"""Alias module mirroring ray.util.scheduling_strategies."""

from ray_tpu.core.scheduling_strategies import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)
