"""joblib backend over the runtime (reference: python/ray/util/joblib/
— ``register_ray()`` + ``with joblib.parallel_backend("ray")``).

``register_ray_tpu()`` registers a ``"ray_tpu"`` joblib backend that
runs joblib's batched calls on the distributed ``Pool`` shim
(util/multiprocessing.py: pool actors on the cluster), so
sklearn-style ``Parallel(n_jobs=...)`` code fans out over the runtime
unchanged. ``n_jobs=-1`` sizes to the cluster's total CPU resources,
not the local host's.
"""

from __future__ import annotations

import ray_tpu


def _cluster_cpu_count() -> int:
    try:
        from ray_tpu import state

        total = 0.0
        for node in state.list_nodes():
            if node.get("state") == "ALIVE":
                total += float((node.get("resources") or {}).get("CPU", 0))
        if total >= 1:
            return int(total)
    except Exception:  # noqa: BLE001 — sizing fallback, never fatal
        pass
    import os

    return os.cpu_count() or 1


def _backend_base():
    """Build the backend class lazily so importing this module never
    hard-requires joblib."""
    from joblib._parallel_backends import MultiprocessingBackend

    class _RayTpuBackend(MultiprocessingBackend):
        def effective_n_jobs(self, n_jobs):
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 in Parallel has no meaning")
            if n_jobs is None:
                return 1
            if n_jobs < 0:
                # -1 = every cluster CPU slot (reference: RayBackend
                # sizing against ray.cluster_resources, not cpu_count)
                n_jobs = max(_cluster_cpu_count() + 1 + n_jobs, 1)
            return n_jobs

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **kwargs):
            from joblib._parallel_backends import (
                FallbackToBackend,
                SequentialBackend,
            )

            # literal 1/None falls back to sequential WITHOUT paying
            # cluster startup; only negative n_jobs needs the cluster
            # connected first so sizing sees cluster CPUs, not the host
            if n_jobs in (None, 1):
                raise FallbackToBackend(
                    SequentialBackend(nesting_level=self.nesting_level))
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            n_jobs = self.effective_n_jobs(n_jobs)
            if n_jobs == 1:
                raise FallbackToBackend(
                    SequentialBackend(nesting_level=self.nesting_level))
            from ray_tpu.util.multiprocessing import Pool

            self._pool = Pool(processes=n_jobs)
            self.parallel = parallel
            return n_jobs

    return _RayTpuBackend


def register_ray_tpu() -> None:
    """Register the ``"ray_tpu"`` joblib parallel backend."""
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", _backend_base())


# reference-compatible alias
register_ray = register_ray_tpu
