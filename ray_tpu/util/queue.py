"""Distributed FIFO queue shared between drivers, tasks, and actors.

API parity with the reference's ray.util.Queue (python/ray/util/queue.py):
put/get with block/timeout, put_nowait/get_nowait, batch variants, qsize.
The reference hosts the buffer in an asyncio actor; here the buffer lives in
a plain actor with non-blocking methods and the *client* polls with backoff —
our actor model executes one method at a time, so a method that blocked
inside the actor would wedge every other client.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        from collections import deque

        self._maxsize = maxsize  # 0 = unbounded
        self._buf = deque()

    def qsize(self) -> int:
        return len(self._buf)

    def put_nowait(self, item) -> bool:
        if self._maxsize > 0 and len(self._buf) >= self._maxsize:
            return False
        self._buf.append(item)
        return True

    def put_nowait_batch(self, items: List[Any]) -> bool:
        if self._maxsize > 0 and len(self._buf) + len(items) > self._maxsize:
            return False
        self._buf.extend(items)
        return True

    def get_nowait(self):
        if not self._buf:
            return False, None
        return True, self._buf.popleft()

    def get_nowait_batch(self, n: int):
        # All-or-nothing, like the reference's Queue.get_nowait_batch.
        if len(self._buf) < n:
            return None
        return [self._buf.popleft() for _ in range(n)]

    def shutdown(self):
        self._buf.clear()


_POLL_START_S = 0.001
_POLL_MAX_S = 0.05


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        actor_options = dict(actor_options or {})
        actor_options.setdefault("num_cpus", 0)
        self.maxsize = maxsize
        self.actor = _QueueActor.options(**actor_options).remote(maxsize)

    def __getstate__(self):
        return {"maxsize": self.maxsize, "actor": self.actor}

    def __setstate__(self, state):
        self.maxsize = state["maxsize"]
        self.actor = state["actor"]

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def put(self, item, block: bool = True, timeout: Optional[float] = None):
        if not block:
            return self.put_nowait(item)
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = _POLL_START_S
        while True:
            if ray_tpu.get(self.actor.put_nowait.remote(item)):
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise Full("queue is full")
            time.sleep(delay)
            delay = min(delay * 2, _POLL_MAX_S)

    def put_nowait(self, item):
        if not ray_tpu.get(self.actor.put_nowait.remote(item)):
            raise Full("queue is full")

    def put_nowait_batch(self, items: List[Any]):
        if not ray_tpu.get(self.actor.put_nowait_batch.remote(list(items))):
            raise Full("queue has no room for the batch")

    def get(self, block: bool = True, timeout: Optional[float] = None):
        if not block:
            return self.get_nowait()
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = _POLL_START_S
        while True:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if ok:
                return item
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty("queue is empty")
            time.sleep(delay)
            delay = min(delay * 2, _POLL_MAX_S)

    def get_nowait(self):
        ok, item = ray_tpu.get(self.actor.get_nowait.remote())
        if not ok:
            raise Empty("queue is empty")
        return item

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        out = ray_tpu.get(self.actor.get_nowait_batch.remote(num_items))
        if out is None:
            raise Empty(f"queue holds fewer than {num_items} items")
        return out

    def shutdown(self):
        if self.actor is not None:
            ray_tpu.kill(self.actor)
            self.actor = None
