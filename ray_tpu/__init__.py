"""ray_tpu: a TPU-native distributed AI runtime.

Tasks, actors, and distributed objects on a shared-memory object store;
gang/placement-group scheduling over the TPU slice/host/chip topology; mesh
collectives as XLA programs over ICI; streaming datasets; distributed
training, tuning, and serving layers built on JAX/XLA/Pallas.

Core API mirrors the reference framework's (`ray.init/remote/get/put/wait`)
so users can switch with minimal changes:

    import ray_tpu

    ray_tpu.init()

    @ray_tpu.remote
    def f(x):
        return x * 2

    ray_tpu.get(f.remote(21))  # 42

NOTE: this top-level module must stay importable without JAX — worker
processes and the core runtime do not pay the JAX import cost. JAX-dependent
layers live under ray_tpu.parallel / ops / models / train and import lazily.
"""

from ray_tpu._version import __version__  # noqa: F401
from ray_tpu.api import (  # noqa: F401
    cancel,
    free,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    method,
    put,
    remote,
    shutdown,
    timeline,
    wait,
)
from ray_tpu.core.object_ref import ObjectRef, ObjectRefGenerator  # noqa: F401
from ray_tpu.core.runtime_context import get_runtime_context  # noqa: F401
from ray_tpu import exceptions  # noqa: F401

__all__ = [
    "__version__",
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "method",
    "get_actor",
    "timeline",
    "ObjectRef",
    "ObjectRefGenerator",
    "get_runtime_context",
    "exceptions",
]
