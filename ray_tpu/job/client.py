"""JobSubmissionClient: submit/status/logs/stop against the GCS job table.

Reference surface: python/ray/dashboard/modules/job/sdk.py
(JobSubmissionClient.submit_job/get_job_status/get_job_logs/stop_job).
"""

from __future__ import annotations

import enum
import os
import time
import uuid
from typing import Dict, List, Optional, Tuple

from ray_tpu.core.cluster.rpc import RpcClient, cluster_authkey


class JobStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


def _parse_addr(address: str) -> Tuple[str, int]:
    host, _, port = address.rpartition(":")
    return host, int(port)


class JobSubmissionClient:
    def __init__(self, address: str, authkey: Optional[bytes] = None):
        self._gcs = RpcClient(_parse_addr(address),
                              authkey or cluster_authkey())
        self._gcs.call(("ping",))

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   submission_id: Optional[str] = None,
                   metadata: Optional[dict] = None,
                   max_restarts: Optional[int] = None,
                   backoff=None) -> str:
        """Submit an entrypoint for supervised execution.

        ``max_restarts`` bounds how many times a crash-looping
        entrypoint (nonzero exit, or an orphaned claim after the agent
        died) is re-queued — each retry waits exponential backoff with
        full jitter. ``backoff`` tunes the schedule: a float (base
        seconds) or {"base_s", "max_s"}. Defaults come from
        config.job_max_restarts_default / 1s base, 30s cap."""
        from ray_tpu.core.config import config

        job_id = submission_id or f"job_{uuid.uuid4().hex[:10]}"
        if max_restarts is None:
            max_restarts = config.job_max_restarts_default
        if backoff is None:
            backoff = {}
        elif isinstance(backoff, (int, float)):
            backoff = {"base_s": float(backoff)}
        bo = {"base_s": float(backoff.get("base_s", 1.0)),
              "max_s": float(backoff.get("max_s", 30.0))}
        spec = {
            "job_id": job_id,
            "submission_id": job_id,
            "entrypoint": entrypoint,
            "env": (runtime_env or {}).get("env_vars", {}),
            "metadata": metadata or {},
            "status": JobStatus.PENDING.value,
            "submitted_at": time.time(),
            "agent": None,
            "max_restarts": int(max_restarts),
            "backoff": bo,
            "restarts": 0,
            "next_eligible_at": 0.0,
            "lease_expires_at": None,
        }
        if self._gcs.call(("kv", "exists", f"job/{job_id}")):
            raise ValueError(f"job {job_id!r} already exists")
        self._gcs.call(("kv", "put", f"job/{job_id}", spec))
        return job_id

    def get_job_info(self, job_id: str) -> dict:
        spec = self._gcs.call(("kv", "get", f"job/{job_id}"))
        if spec is None:
            raise KeyError(f"no job {job_id!r}")
        return spec

    def get_job_status(self, job_id: str) -> JobStatus:
        return JobStatus(self.get_job_info(job_id)["status"])

    def list_jobs(self) -> List[dict]:
        keys = self._gcs.call(("kv", "keys", "job/"))
        # a job deleted between the keys scan and the per-key get reads
        # back as None — skip it instead of handing callers a None row
        jobs = (self._gcs.call(("kv", "get", k)) for k in keys)
        return [j for j in jobs if j is not None]

    def get_job_logs(self, job_id: str) -> str:
        info = self.get_job_info(job_id)
        path = info.get("log_path")
        if not path or not os.path.exists(path):
            return ""
        with open(path) as f:
            return f.read()

    def stop_job(self, job_id: str) -> bool:
        info = self.get_job_info(job_id)
        if info["status"] == JobStatus.PENDING.value:
            # not claimed yet: flip straight to STOPPED (atomic; if an
            # agent claims concurrently the cas fails and we fall through)
            if self._gcs.call(("kv", "cas_merge", f"job/{job_id}", (
                    {"status": JobStatus.PENDING.value},
                    {"status": JobStatus.STOPPED.value}))) is not None:
                return True
            info = self.get_job_info(job_id)
        if info["status"] == JobStatus.RUNNING.value:
            self._gcs.call(("kv", "merge", f"job/{job_id}",
                            {"stop_requested": True}))
            return True
        return False

    def wait_until_finished(self, job_id: str, timeout: float = 300.0
                            ) -> JobStatus:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                          JobStatus.STOPPED):
                return status
            time.sleep(0.25)
        raise TimeoutError(f"job {job_id} did not finish in {timeout}s")

    def close(self):
        self._gcs.close()
