"""JobAgent: claims PENDING jobs from the GCS table and runs them.

Reference: the JobManager/JobSupervisor pair
(dashboard/modules/job/job_manager.py:58) — there a supervisor actor per
job; here a thread on the head node spawns the entrypoint subprocess with
RTPU_ADDRESS pointing at the cluster, streams logs to a file, honors stop
requests, and writes terminal status back to the table.

Supervision contract:

- every claim carries a heartbeat lease (``lease_expires_at``, renewed
  each poll tick); the GCS orphan detector re-queues or fails any
  RUNNING job whose lease expired, so a SIGKILLed agent cannot strand
  jobs forever
- a crash-looping entrypoint (nonzero exit) is re-queued up to
  ``max_restarts`` times with exponential backoff + full jitter
  (job/backoff.py — the same deterministic schedule the orphan detector
  uses), and ``stop_requested`` holds across every restart boundary
- terminal writes go through cas_merge keyed on this agent's claim, so
  an agent racing the orphan detector (or another agent) loses cleanly
  instead of clobbering

Run standalone as ``python -m ray_tpu.job.agent --gcs host:port`` (the
cluster authkey comes from RTPU_CLUSTER_AUTHKEY) — tests and bench use
this to SIGKILL an agent mid-job and watch lease-expiry recovery.
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import threading
import time
from typing import Dict, Optional, Tuple

from ray_tpu.core import fault_injection
from ray_tpu.core.cluster.rpc import RpcClient, RpcError
from ray_tpu.core.config import config

from ray_tpu.job.backoff import delay_for
from ray_tpu.job.client import JobStatus

logger = logging.getLogger(__name__)


class JobAgent:
    def __init__(self, gcs: RpcClient, gcs_address: Tuple[str, int],
                 agent_id: str, log_dir: str = "/tmp/ray_tpu_jobs",
                 poll_s: float = 0.25):
        self._gcs = gcs
        self._gcs_address = gcs_address
        self._agent_id = agent_id
        self._log_dir = log_dir
        self._poll_s = poll_s
        self._procs: Dict[str, subprocess.Popen] = {}
        self._stop = False
        self._warned_unexpected = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="job-agent")
        self._thread.start()

    def _loop(self):
        while not self._stop:
            try:
                self._claim_pending()
                self._renew_leases()
                self._reap()
            except (RpcError, ConnectionError, TimeoutError, OSError,
                    EOFError):
                # GCS unreachable (failover, partition): transient by
                # construction — the next tick retries, and the lease
                # machinery covers us if we stay cut off too long
                pass
            except Exception:  # noqa: BLE001 — the agent must survive
                # NOT a transport error: a bug or a malformed spec must
                # be visible once, not silently swallowed every tick
                if not self._warned_unexpected:
                    self._warned_unexpected = True
                    logger.warning("job agent loop failed unexpectedly",
                                   exc_info=True)
            time.sleep(self._poll_s)

    def _claim_pending(self):
        now = time.time()
        for key in self._gcs.call(("kv", "keys", "job/")):
            spec = self._gcs.call(("kv", "get", key))
            if not spec or spec.get("status") != JobStatus.PENDING.value:
                continue
            if spec.get("stop_requested"):
                # stop holds across restart boundaries: a job stopped
                # while RUNNING must not run its backoff re-queue
                self._gcs.call(("kv", "cas_merge", key, (
                    {"status": JobStatus.PENDING.value},
                    {"status": JobStatus.STOPPED.value,
                     "finished_at": now})))
                continue
            if (spec.get("next_eligible_at") or 0) > now:
                continue  # crash-loop backoff window still open
            os.makedirs(self._log_dir, exist_ok=True)
            log_path = os.path.join(self._log_dir,
                                    f"{spec['job_id']}.log")
            # atomic claim: only one agent flips PENDING -> RUNNING, and a
            # concurrent stop_job's merge can't be overwritten. The claim
            # carries this agent's lease; _renew_leases keeps it fresh.
            claimed = self._gcs.call(("kv", "cas_merge", key, (
                {"status": JobStatus.PENDING.value},
                {"status": JobStatus.RUNNING.value,
                 "agent": self._agent_id, "log_path": log_path,
                 "started_at": now,
                 "lease_expires_at": now + config.job_lease_ttl_s})))
            if claimed is None:
                continue
            spec = claimed
            if fault_injection.enabled() and fault_injection.fire(
                    "job_claim", spec["job_id"]) == "drop":
                # chaos: the agent "dies" right after claiming — abandon
                # the claim without spawning; lease expiry must recover
                continue
            stale_pid = spec.get("pid")
            if stale_pid and (spec.get("orphaned")
                              or int(spec.get("restarts") or 0) > 0):
                # re-claim after an agent death: the previous attempt's
                # process group may still be running (start_new_session
                # outlives the agent) — reap it so the job never runs
                # twice concurrently
                try:
                    os.killpg(stale_pid, signal.SIGKILL)
                except OSError:
                    pass
            # append on retries so earlier attempts' output survives
            log = open(log_path,
                       "a" if int(spec.get("restarts") or 0) else "w")
            try:
                env = dict(os.environ)
                env.update(spec.get("env") or {})
                env["RTPU_ADDRESS"] = (
                    f"{self._gcs_address[0]}:{self._gcs_address[1]}")
                try:
                    proc = subprocess.Popen(
                        spec["entrypoint"], shell=True, env=env,
                        stdout=log, stderr=subprocess.STDOUT,
                        start_new_session=True)
                except OSError as e:
                    self._gcs.call(("kv", "merge", key, {
                        "status": JobStatus.FAILED.value,
                        "error": repr(e)}))
                    continue
            finally:
                # the child holds its own dup of the fd; keeping ours
                # open leaks one fd per claim (and a failed Popen used
                # to leak it forever)
                log.close()
            self._procs[spec["job_id"]] = proc
            self._gcs.call(("kv", "merge", key, {"pid": proc.pid}))

    def _renew_leases(self):
        now = time.time()
        for job_id in list(self._procs):
            self._gcs.call(("kv", "cas_merge", f"job/{job_id}", (
                {"status": JobStatus.RUNNING.value,
                 "agent": self._agent_id},
                {"lease_expires_at": now + config.job_lease_ttl_s})))

    def _reap(self):
        for job_id, proc in list(self._procs.items()):
            key = f"job/{job_id}"
            spec = self._gcs.call(("kv", "get", key)) or {}
            if spec.get("agent") != self._agent_id or \
                    spec.get("status") != JobStatus.RUNNING.value:
                # the orphan detector (or an operator) took the job from
                # us — a lease we let lapse. Kill our copy: the table's
                # owner decides what runs, never two agents at once.
                if proc.poll() is None:
                    try:
                        os.killpg(proc.pid, signal.SIGKILL)
                    except OSError:
                        pass
                del self._procs[job_id]
                continue
            if spec.get("stop_requested") and proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGTERM)
                except OSError:
                    pass
                try:
                    proc.wait(5)
                except subprocess.TimeoutExpired:
                    try:
                        os.killpg(proc.pid, signal.SIGKILL)
                    except OSError:
                        pass
                self._gcs.call(("kv", "merge", key, {
                    "status": JobStatus.STOPPED.value,
                    "lease_expires_at": None,
                    "finished_at": time.time()}))
                del self._procs[job_id]
                continue
            rc = proc.poll()
            if rc is None:
                continue
            restarts = int(spec.get("restarts") or 0)
            max_restarts = int(spec.get("max_restarts") or 0)
            if rc == 0:
                updates = {"status": JobStatus.SUCCEEDED.value,
                           "returncode": rc, "lease_expires_at": None,
                           "finished_at": time.time()}
            elif spec.get("stop_requested"):
                # the process died while we were about to stop it —
                # report STOPPED, not a crash-loop retry
                updates = {"status": JobStatus.STOPPED.value,
                           "returncode": rc, "lease_expires_at": None,
                           "finished_at": time.time()}
            elif restarts < max_restarts:
                delay = delay_for(spec.get("submission_id") or job_id,
                                  restarts,
                                  (spec.get("backoff") or {})
                                  .get("base_s", 1.0),
                                  (spec.get("backoff") or {})
                                  .get("max_s", 30.0))
                updates = {"status": JobStatus.PENDING.value,
                           "agent": None, "returncode": rc,
                           "restarts": restarts + 1,
                           "next_eligible_at": time.time() + delay,
                           "lease_expires_at": None,
                           "backoff_history":
                               list(spec.get("backoff_history") or [])
                               + [delay]}
            else:
                updates = {"status": JobStatus.FAILED.value,
                           "returncode": rc, "lease_expires_at": None,
                           "finished_at": time.time()}
            # cas on our own claim: if the orphan detector re-queued the
            # job between our poll and now, it owns the next attempt and
            # this write must lose
            self._gcs.call(("kv", "cas_merge", key, (
                {"status": JobStatus.RUNNING.value,
                 "agent": self._agent_id}, updates)))
            del self._procs[job_id]

    def close(self):
        self._stop = True
        for job_id, proc in list(self._procs.items()):
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except OSError:
                    pass
            # record a terminal status so clients never spin on RUNNING
            try:
                # rtpu-lint: disable=L9 — per-job fan-out on the
                # shutdown path: the merge applies at most once per job,
                # and if it is lost the lease-expiry orphan scan redoes
                # the bookkeeping once the lease runs out
                self._gcs.call(("kv", "merge", f"job/{job_id}", {
                    "status": JobStatus.STOPPED.value,
                    "lease_expires_at": None,
                    "finished_at": time.time(),
                    "error": "job agent shut down"}))
            # rtpu-lint: disable=L4 — shutdown path: the terminal-status
            # write is best-effort (the GCS may already be gone, fenced,
            # or mid-failover); nothing here can act on the error
            except Exception:  # noqa: BLE001
                pass


def main(argv=None):
    """Standalone agent process (tests/bench SIGKILL this to exercise
    lease-expiry orphan recovery)."""
    import argparse
    import sys
    import uuid

    from ray_tpu.core.cluster.rpc import cluster_authkey

    p = argparse.ArgumentParser(description="ray_tpu job agent")
    p.add_argument("--gcs", required=True, help="host:port of the GCS")
    p.add_argument("--agent-id", default=None)
    p.add_argument("--log-dir", default="/tmp/ray_tpu_jobs")
    p.add_argument("--poll", type=float, default=0.25)
    args = p.parse_args(argv)
    host, _, port = args.gcs.rpartition(":")
    addr = (host, int(port))
    gcs = RpcClient(addr, cluster_authkey())
    agent = JobAgent(gcs, addr,
                     agent_id=args.agent_id or uuid.uuid4().hex[:12],
                     log_dir=args.log_dir, poll_s=args.poll)
    print("AGENT_READY", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    agent.close()
    gcs.close()
    sys.exit(0)


if __name__ == "__main__":
    main()
