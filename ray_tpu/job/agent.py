"""JobAgent: claims PENDING jobs from the GCS table and runs them.

Reference: the JobManager/JobSupervisor pair
(dashboard/modules/job/job_manager.py:58) — there a supervisor actor per
job; here a thread on the head node spawns the entrypoint subprocess with
RTPU_ADDRESS pointing at the cluster, streams logs to a file, honors stop
requests, and writes terminal status back to the table.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
from typing import Dict, Optional, Tuple

from ray_tpu.core.cluster.rpc import RpcClient

from ray_tpu.job.client import JobStatus


class JobAgent:
    def __init__(self, gcs: RpcClient, gcs_address: Tuple[str, int],
                 agent_id: str, log_dir: str = "/tmp/ray_tpu_jobs",
                 poll_s: float = 0.25):
        self._gcs = gcs
        self._gcs_address = gcs_address
        self._agent_id = agent_id
        self._log_dir = log_dir
        self._poll_s = poll_s
        self._procs: Dict[str, subprocess.Popen] = {}
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="job-agent")
        self._thread.start()

    def _loop(self):
        while not self._stop:
            try:
                self._claim_pending()
                self._reap()
            except Exception:  # noqa: BLE001 — the agent must survive
                pass
            time.sleep(self._poll_s)

    def _claim_pending(self):
        for key in self._gcs.call(("kv", "keys", "job/")):
            spec = self._gcs.call(("kv", "get", key))
            if not spec or spec.get("status") != JobStatus.PENDING.value:
                continue
            os.makedirs(self._log_dir, exist_ok=True)
            log_path = os.path.join(self._log_dir,
                                    f"{spec['job_id']}.log")
            # atomic claim: only one agent flips PENDING -> RUNNING, and a
            # concurrent stop_job's merge can't be overwritten
            claimed = self._gcs.call(("kv", "cas_merge", key, (
                {"status": JobStatus.PENDING.value},
                {"status": JobStatus.RUNNING.value,
                 "agent": self._agent_id, "log_path": log_path})))
            if claimed is None:
                continue
            spec = claimed
            env = dict(os.environ)
            env.update(spec.get("env") or {})
            env["RTPU_ADDRESS"] = (
                f"{self._gcs_address[0]}:{self._gcs_address[1]}")
            log = open(log_path, "w")
            try:
                proc = subprocess.Popen(
                    spec["entrypoint"], shell=True, env=env,
                    stdout=log, stderr=subprocess.STDOUT,
                    start_new_session=True)
            except OSError as e:
                self._gcs.call(("kv", "merge", key, {
                    "status": JobStatus.FAILED.value, "error": repr(e)}))
                continue
            self._procs[spec["job_id"]] = proc
            self._gcs.call(("kv", "merge", key, {"pid": proc.pid}))

    def _reap(self):
        for job_id, proc in list(self._procs.items()):
            key = f"job/{job_id}"
            spec = self._gcs.call(("kv", "get", key)) or {}
            if spec.get("stop_requested") and proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGTERM)
                except OSError:
                    pass
                try:
                    proc.wait(5)
                except subprocess.TimeoutExpired:
                    try:
                        os.killpg(proc.pid, signal.SIGKILL)
                    except OSError:
                        pass
                self._gcs.call(("kv", "merge", key, {
                    "status": JobStatus.STOPPED.value,
                    "finished_at": time.time()}))
                del self._procs[job_id]
                continue
            rc = proc.poll()
            if rc is None:
                continue
            self._gcs.call(("kv", "merge", key, {
                "status": (JobStatus.SUCCEEDED.value if rc == 0
                           else JobStatus.FAILED.value),
                "returncode": rc, "finished_at": time.time()}))
            del self._procs[job_id]

    def close(self):
        self._stop = True
        for job_id, proc in list(self._procs.items()):
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except OSError:
                    pass
            # record a terminal status so clients never spin on RUNNING
            try:
                self._gcs.call(("kv", "merge", f"job/{job_id}", {
                    "status": JobStatus.STOPPED.value,
                    "finished_at": time.time(),
                    "error": "job agent shut down"}))
            except Exception:  # noqa: BLE001 — GCS may be gone too
                pass
