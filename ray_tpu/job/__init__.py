"""Job submission (reference: python/ray/dashboard/modules/job/ —
JobSubmissionClient sdk.py, JobManager job_manager.py:58).

Jobs are driver scripts run as subprocesses against the cluster: the
client records the job spec in the GCS job table (cluster KV under
``job/``), a JobAgent on one node claims it, spawns the entrypoint with
RTPU_ADDRESS pointing at the GCS, captures logs, and updates status.
"""

from ray_tpu.job.client import JobStatus, JobSubmissionClient  # noqa: F401
from ray_tpu.job.agent import JobAgent  # noqa: F401
