"""Crash-loop backoff for supervised jobs: exponential with FULL
jitter (reference: the AWS architecture-blog schedule the reference
runtime uses for actor restarts — delay ~ U(0, min(max, base * 2^n))).

Deterministic on (job_id, attempt): the agent that re-queues a crashed
job and the GCS orphan detector that re-queues a leased-out one compute
the SAME delay for the same attempt, so tests can replay the schedule
and two writers never fight over next_eligible_at.
"""

from __future__ import annotations

import random


def delay_for(job_id: str, attempt: int, base_s: float = 1.0,
              max_s: float = 30.0) -> float:
    """Seconds to wait before retry number ``attempt`` (0-based)."""
    cap = min(float(max_s), float(base_s) * (2 ** max(0, int(attempt))))
    return random.Random(f"{job_id}:{attempt}").uniform(0.0, cap)
