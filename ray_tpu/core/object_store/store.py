"""Python client for the shared-memory object store.

Zero-copy reads: ``get`` returns a memoryview directly over the shared
mapping; the object stays pinned (refcount) until ``release``. The plasma
equivalent in the reference exposes the same create/seal/get/release/delete
lifecycle (src/ray/object_manager/plasma/client.h), but over a unix-socket
protocol — here every process talks to the mapping directly.
"""

from __future__ import annotations

import ctypes
import mmap
import os
from typing import Optional

from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store.build import ensure_built
from ray_tpu.exceptions import ObjectStoreFullError, ObjectTimeoutError


def _load_lib() -> ctypes.CDLL:
    # RTPU_STORE_LIB: sanitizer harness loads an instrumented build
    # (tests/test_store_sanitize.py; build.py --sanitize={thread,address})
    override = os.environ.get("RTPU_STORE_LIB")
    try:
        lib = ctypes.CDLL(override or ensure_built())
    except OSError:
        if override:
            raise
        # a shipped/cached binary can be ABI-incompatible with this host
        # (built against a newer glibc); recompile from source and retry
        lib = ctypes.CDLL(ensure_built(force=True))
    if not hasattr(lib, "rtpu_chan_wait_spin") and not override:
        # cached .so predates the spin entry point; rebuild from source
        lib = ctypes.CDLL(ensure_built(force=True))
    lib.rtpu_store_create.restype = ctypes.c_void_p
    lib.rtpu_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
    lib.rtpu_store_connect.restype = ctypes.c_void_p
    lib.rtpu_store_connect.argtypes = [ctypes.c_char_p]
    lib.rtpu_store_close.argtypes = [ctypes.c_void_p]
    lib.rtpu_store_destroy.argtypes = [ctypes.c_char_p]
    lib.rtpu_store_base.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.rtpu_store_base.argtypes = [ctypes.c_void_p]
    lib.rtpu_store_mapping_size.restype = ctypes.c_uint64
    lib.rtpu_store_mapping_size.argtypes = [ctypes.c_void_p]
    lib.rtpu_store_create_object.restype = ctypes.c_uint64
    lib.rtpu_store_create_object.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    lib.rtpu_store_seal.restype = ctypes.c_int
    lib.rtpu_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_store_seal_retain.restype = ctypes.c_int
    lib.rtpu_store_seal_retain.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_store_get.restype = ctypes.c_int
    lib.rtpu_store_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.rtpu_store_release.restype = ctypes.c_int
    lib.rtpu_store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_store_contains.restype = ctypes.c_int
    lib.rtpu_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_store_delete.restype = ctypes.c_int
    lib.rtpu_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_store_stats.argtypes = [ctypes.c_void_p] + [ctypes.POINTER(ctypes.c_uint64)] * 4
    lib.rtpu_store_prefault.argtypes = [ctypes.c_void_p]
    lib.rtpu_store_refcount.restype = ctypes.c_int64
    lib.rtpu_store_refcount.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_chan_header_size.restype = ctypes.c_uint64
    lib.rtpu_chan_header_size.argtypes = []
    lib.rtpu_chan_init.restype = ctypes.c_int
    lib.rtpu_chan_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.rtpu_chan_seqno.restype = ctypes.c_uint64
    lib.rtpu_chan_seqno.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                    ctypes.c_int]
    lib.rtpu_chan_post.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.c_int, ctypes.c_uint64]
    lib.rtpu_chan_wait.restype = ctypes.c_uint64
    lib.rtpu_chan_wait.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.c_int, ctypes.c_uint64,
                                   ctypes.c_int]
    if hasattr(lib, "rtpu_chan_wait_spin"):
        # an RTPU_STORE_LIB override built before the spin entry point
        # stays usable: chan_wait_spin falls back to the blocking wait
        lib.rtpu_chan_wait_spin.restype = ctypes.c_uint64
        lib.rtpu_chan_wait_spin.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_uint64, ctypes.c_int, ctypes.c_uint32]
    return lib


_lib: Optional[ctypes.CDLL] = None


def _get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = _load_lib()
    return _lib


class ShmObjectStore:
    """Handle to a shared-memory object store (creator or connected client)."""

    def __init__(self, name: str, handle: int, owner: bool):
        self._name = name
        self._handle = handle
        self._owner = owner
        # Optional backpressure hook: called with a byte count when an
        # allocation fails; returns True if space may have been freed
        # (spilling). The runtime installs its spill manager here; workers
        # install an RPC to the owner.
        self.need_space_hook = None
        lib = _get_lib()
        size = lib.rtpu_store_mapping_size(handle)
        base = lib.rtpu_store_base(handle)
        # A writable zero-copy view over the whole mapping.
        self._mv = memoryview(
            ctypes.cast(base, ctypes.POINTER(ctypes.c_uint8 * size)).contents
        ).cast("B")

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    def create(cls, name: str, capacity: int, table_slots: int = 0) -> "ShmObjectStore":
        handle = _get_lib().rtpu_store_create(name.encode(), capacity, table_slots)
        if not handle:
            raise OSError(f"failed to create shm store {name!r}")
        return cls(name, handle, owner=True)

    @classmethod
    def connect(cls, name: str) -> "ShmObjectStore":
        handle = _get_lib().rtpu_store_connect(name.encode())
        if not handle:
            raise OSError(f"failed to connect to shm store {name!r}")
        return cls(name, handle, owner=False)

    def close(self):
        if self._handle:
            try:
                self._mv.release()
            except BufferError:
                pass  # zero-copy views still exported; mapping stays alive
            _get_lib().rtpu_store_close(self._handle)
            if self._owner:
                _get_lib().rtpu_store_destroy(self._name.encode())
            self._handle = 0

    @property
    def name(self) -> str:
        return self._name

    # -- object lifecycle ----------------------------------------------------

    def _h(self) -> int:
        if not self._handle:
            raise ValueError("object store is closed")
        return self._handle

    def create_object(self, oid: ObjectID, size: int) -> memoryview:
        """Allocate an unsealed object; returns a writable view of its payload."""
        off = _get_lib().rtpu_store_create_object(self._h(), oid.binary(), size)
        if off == 0:
            raise ObjectStoreFullError(
                f"cannot allocate {size} bytes for {oid} (store full or duplicate)"
            )
        return self._mv[off : off + size]

    def seal(self, oid: ObjectID, retain: bool = False):
        """Seal an object. With ``retain`` the creator reference is kept
        (refcount >= 1) for handoff to the owner's tracking pin — there is
        never an evictable refcount==0 window for a live object."""
        fn = (_get_lib().rtpu_store_seal_retain if retain
              else _get_lib().rtpu_store_seal)
        if fn(self._h(), oid.binary()) != 0:
            raise ValueError(f"seal failed for {oid}")

    def put(self, oid: ObjectID, data, retain: bool = False) -> None:
        """Allocate + copy + seal in one call."""
        view = memoryview(data).cast("B")
        dst = self.create_object(oid, view.nbytes)
        dst[:] = view
        self.seal(oid, retain=retain)

    def get(self, oid: ObjectID, timeout_ms: int = -1) -> memoryview:
        """Blocking get; returns a zero-copy read view, pinning the object.

        Call :meth:`release` when the view is no longer needed.
        """
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = _get_lib().rtpu_store_get(
            self._h(), oid.binary(), timeout_ms, ctypes.byref(off), ctypes.byref(size)
        )
        if rc != 0:
            raise ObjectTimeoutError(f"object {oid} not available within {timeout_ms}ms")
        return self._mv[off.value : off.value + size.value]

    def release(self, oid: ObjectID):
        # Pin finalizers (zero-copy numpy views) can fire at interpreter
        # exit, after close(); the C handle is freed then — never call in.
        if not self._handle:
            return
        _get_lib().rtpu_store_release(self._handle, oid.binary())

    def contains(self, oid: ObjectID) -> bool:
        return bool(_get_lib().rtpu_store_contains(self._h(), oid.binary()))

    def refcount(self, oid: ObjectID) -> int:
        """Current refcount (-1 if absent)."""
        return int(_get_lib().rtpu_store_refcount(self._h(), oid.binary()))

    def create_object_with_pressure(self, oid: ObjectID, size: int
                                    ) -> memoryview:
        """create_object, invoking the need_space hook and retrying once
        when the store is full."""
        try:
            return self.create_object(oid, size)
        except ObjectStoreFullError:
            hook = self.need_space_hook
            if hook is None or not hook(size):
                raise
            return self.create_object(oid, size)

    def delete(self, oid: ObjectID):
        _get_lib().rtpu_store_delete(self._h(), oid.binary())

    def prefault(self):
        """Blocking eager population of the heap (content-preserving)."""
        _get_lib().rtpu_store_prefault(self._h())

    # -- channel primitives (seqno-gated mutable regions; see dag/channel.py)

    def object_offset(self, oid: ObjectID) -> int:
        """Mapping offset of a sealed object's payload (pins it)."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = _get_lib().rtpu_store_get(
            self._h(), oid.binary(), 0, ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            raise ObjectTimeoutError(f"object {oid} not found")
        return off.value

    def chan_header_size(self) -> int:
        return int(_get_lib().rtpu_chan_header_size())

    def chan_init(self, offset: int):
        if _get_lib().rtpu_chan_init(self._h(), offset) != 0:
            raise OSError("channel init failed")

    def chan_counter(self, offset: int, which: int) -> int:
        return int(_get_lib().rtpu_chan_seqno(self._h(), offset, which))

    def chan_post(self, offset: int, which: int, value: int):
        _get_lib().rtpu_chan_post(self._h(), offset, which, value)

    def chan_wait(self, offset: int, which: int, last: int,
                  timeout_ms: int) -> int:
        return int(_get_lib().rtpu_chan_wait(self._h(), offset, which, last,
                                             timeout_ms))

    def chan_wait_spin(self, offset: int, which: int, last: int,
                       timeout_ms: int, spin_us: int) -> int:
        """chan_wait with a busy-poll budget of ``spin_us`` microseconds
        before the condvar fallback (0 = pure block). Degrades to
        chan_wait under an RTPU_STORE_LIB override lacking the symbol."""
        lib = _get_lib()
        if spin_us <= 0 or not hasattr(lib, "rtpu_chan_wait_spin"):
            return int(lib.rtpu_chan_wait(self._h(), offset, which, last,
                                          timeout_ms))
        return int(lib.rtpu_chan_wait_spin(self._h(), offset, which, last,
                                           timeout_ms, spin_us))

    def view(self, offset: int, size: int) -> memoryview:
        return self._mv[offset: offset + size]

    def stats(self) -> dict:
        vals = [ctypes.c_uint64() for _ in range(4)]
        _get_lib().rtpu_store_stats(self._h(), *[ctypes.byref(v) for v in vals])
        return {
            "heap_size": vals[0].value,
            "bytes_in_use": vals[1].value,
            "num_objects": vals[2].value,
            "evictions": vals[3].value,
        }


def default_store_capacity() -> int:
    """A configurable fraction of system memory (default 30%), capped at
    4 GiB (single host; same heuristic as the reference —
    python/ray/_private/ray_constants.py)."""
    from ray_tpu.core.config import config

    try:
        total = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError):
        total = 8 << 30
    return min(int(total * config.object_store_memory_fraction), 4 << 30)
