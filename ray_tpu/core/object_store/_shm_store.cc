// Shared-memory object store: the plasma equivalent for the TPU-native runtime.
//
// Reference behavior mirrored (not code): src/ray/object_manager/plasma/ —
// an immutable object store in shared memory with create→seal lifecycle,
// per-object refcounts, and LRU eviction of unreferenced sealed objects
// (ref: object_lifecycle_manager.h, eviction_policy.h). Differences by design:
// instead of a store server process + unix-socket client protocol with fd
// passing (ref: store.h, fling.cc), the allocator and object table live *in*
// the shared mapping guarded by a process-shared robust mutex, so every
// worker allocates/looks up directly with no RPC. This removes the socket
// round-trip from the put/get hot path entirely.
//
// Layout of the shared mapping:
//   [StoreHeader | slot table | heap]
// Free heap blocks form an offset-sorted singly-linked free list with
// coalescing on free (dlmalloc in the reference; first-fit is adequate since
// large-object memcpy dominates allocation cost).
//
// Build: g++ -O2 -shared -fPIC -o _shm_store.so _shm_store.cc -lpthread -lrt

#include <cstdint>
#include <cstring>
#include <cerrno>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5250554153544f52ULL;  // "RPUASTOR"
constexpr uint32_t kIdLen = 16;
constexpr uint64_t kAlign = 64;

// Slot states. TOMBSTONE keeps open-addressing probe chains intact after
// eviction/delete; inserts reuse tombstones.
enum : uint32_t { SLOT_EMPTY = 0, SLOT_CREATED = 1, SLOT_SEALED = 2, SLOT_TOMBSTONE = 3 };

struct Slot {
  uint8_t id[kIdLen];
  uint32_t state;
  uint32_t _pad;
  uint64_t data_offset;  // offset of payload in mapping
  uint64_t data_size;
  int64_t refcount;
  // LRU doubly-linked list of evictable (sealed, refcount==0) slots.
  // Values are slot_index + 1; 0 means "none".
  uint64_t lru_prev;
  uint64_t lru_next;
};

struct FreeBlock {
  uint64_t size;         // bytes including this header
  uint64_t next_offset;  // offset of next free block, 0 = end
};

struct StoreHeader {
  uint64_t magic;
  uint64_t mapping_size;
  uint64_t heap_offset;
  uint64_t heap_size;
  uint32_t table_slots;  // power of two
  uint32_t _pad;
  uint64_t free_head;        // offset of first free block (0 = none)
  uint64_t bytes_in_use;     // allocated payload bytes
  uint64_t num_objects;
  uint64_t lru_head;         // slot_index + 1
  uint64_t lru_tail;
  uint64_t evictions;
  pthread_mutex_t mutex;
  pthread_cond_t seal_cond;
};

struct Store {
  uint8_t* base;
  uint64_t size;
  StoreHeader* hdr;
  Slot* slots;
};

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

inline uint64_t id_hash(const uint8_t* id) {
  uint64_t h;
  memcpy(&h, id, 8);
  uint64_t h2;
  memcpy(&h2, id + 8, 8);
  h ^= h2 * 0x9e3779b97f4a7c15ULL;
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdULL;
  h ^= h >> 33;
  return h;
}

void lock(Store* s) {
  int rc = pthread_mutex_lock(&s->hdr->mutex);
  if (rc == EOWNERDEAD) {
    // A worker died holding the lock; the table may be mid-update but all
    // our critical sections leave it structurally consistent at each store.
    pthread_mutex_consistent(&s->hdr->mutex);
  }
}

void unlock(Store* s) { pthread_mutex_unlock(&s->hdr->mutex); }

// Lookup an existing (created/sealed) entry; nullptr if absent.
Slot* find_slot(Store* s, const uint8_t* id, bool /*unused*/ = false) {
  uint32_t mask = s->hdr->table_slots - 1;
  uint64_t idx = id_hash(id) & mask;
  for (uint32_t probe = 0; probe <= mask; ++probe, idx = (idx + 1) & mask) {
    Slot* slot = &s->slots[idx];
    if (slot->state == SLOT_EMPTY) return nullptr;
    if (slot->state != SLOT_TOMBSTONE && memcmp(slot->id, id, kIdLen) == 0)
      return slot;
  }
  return nullptr;
}

// Find a slot to insert `id` into, reusing tombstones. Returns nullptr if the
// id already exists or the table is full.
Slot* find_insert_slot(Store* s, const uint8_t* id) {
  uint32_t mask = s->hdr->table_slots - 1;
  uint64_t idx = id_hash(id) & mask;
  Slot* reusable = nullptr;
  for (uint32_t probe = 0; probe <= mask; ++probe, idx = (idx + 1) & mask) {
    Slot* slot = &s->slots[idx];
    if (slot->state == SLOT_EMPTY) return reusable ? reusable : slot;
    if (slot->state == SLOT_TOMBSTONE) {
      if (!reusable) reusable = slot;
    } else if (memcmp(slot->id, id, kIdLen) == 0) {
      return nullptr;  // duplicate
    }
  }
  return reusable;
}

inline uint64_t slot_index(Store* s, Slot* slot) {
  return static_cast<uint64_t>(slot - s->slots);
}

void lru_unlink(Store* s, Slot* slot) {
  uint64_t me = slot_index(s, slot) + 1;
  StoreHeader* h = s->hdr;
  if (slot->lru_prev)
    s->slots[slot->lru_prev - 1].lru_next = slot->lru_next;
  else if (h->lru_head == me)
    h->lru_head = slot->lru_next;
  if (slot->lru_next)
    s->slots[slot->lru_next - 1].lru_prev = slot->lru_prev;
  else if (h->lru_tail == me)
    h->lru_tail = slot->lru_prev;
  slot->lru_prev = slot->lru_next = 0;
}

void lru_push_back(Store* s, Slot* slot) {
  uint64_t me = slot_index(s, slot) + 1;
  StoreHeader* h = s->hdr;
  slot->lru_prev = h->lru_tail;
  slot->lru_next = 0;
  if (h->lru_tail)
    s->slots[h->lru_tail - 1].lru_next = me;
  else
    h->lru_head = me;
  h->lru_tail = me;
}

// Free-list insert with coalescing; list kept sorted by offset.
void heap_free(Store* s, uint64_t offset, uint64_t size) {
  StoreHeader* h = s->hdr;
  uint64_t prev = 0, cur = h->free_head;
  while (cur && cur < offset) {
    prev = cur;
    cur = reinterpret_cast<FreeBlock*>(s->base + cur)->next_offset;
  }
  FreeBlock* nb = reinterpret_cast<FreeBlock*>(s->base + offset);
  nb->size = size;
  nb->next_offset = cur;
  if (prev) {
    FreeBlock* pb = reinterpret_cast<FreeBlock*>(s->base + prev);
    pb->next_offset = offset;
    if (prev + pb->size == offset) {  // merge prev+new
      pb->size += nb->size;
      pb->next_offset = nb->next_offset;
      nb = pb;
      offset = prev;
    }
  } else {
    h->free_head = offset;
  }
  if (cur && offset + nb->size == cur) {  // merge new+next
    FreeBlock* cb = reinterpret_cast<FreeBlock*>(s->base + cur);
    nb->size += cb->size;
    nb->next_offset = cb->next_offset;
  }
}

// First-fit allocation. Returns payload offset or 0 on failure.
uint64_t heap_alloc(Store* s, uint64_t payload) {
  uint64_t need = align_up(payload);
  StoreHeader* h = s->hdr;
  uint64_t prev = 0, cur = h->free_head;
  while (cur) {
    FreeBlock* b = reinterpret_cast<FreeBlock*>(s->base + cur);
    if (b->size >= need) {
      uint64_t remaining = b->size - need;
      if (remaining >= kAlign) {
        // Split: keep remainder as a free block at the tail.
        uint64_t rem_off = cur + need;
        FreeBlock* rb = reinterpret_cast<FreeBlock*>(s->base + rem_off);
        rb->size = remaining;
        rb->next_offset = b->next_offset;
        if (prev)
          reinterpret_cast<FreeBlock*>(s->base + prev)->next_offset = rem_off;
        else
          h->free_head = rem_off;
      } else {
        need = b->size;  // absorb the sliver
        if (prev)
          reinterpret_cast<FreeBlock*>(s->base + prev)->next_offset = b->next_offset;
        else
          h->free_head = b->next_offset;
      }
      h->bytes_in_use += need;
      return cur;
    }
    prev = cur;
    cur = b->next_offset;
  }
  return 0;
}

// Evict one LRU object. Caller holds lock. Returns false if nothing evictable.
bool evict_one(Store* s) {
  StoreHeader* h = s->hdr;
  if (!h->lru_head) return false;
  Slot* victim = &s->slots[h->lru_head - 1];
  lru_unlink(s, victim);
  heap_free(s, victim->data_offset, align_up(victim->data_size));
  h->bytes_in_use -= align_up(victim->data_size);
  h->num_objects--;
  h->evictions++;
  victim->state = SLOT_TOMBSTONE;
  return true;
}

void timespec_in(struct timespec* ts, int timeout_ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += static_cast<long>(timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

}  // namespace

extern "C" {

// Create a new store backed by shm file `name` with ~`capacity` heap bytes.
// Returns opaque handle or null.
void* rtpu_store_create(const char* name, uint64_t capacity, uint32_t table_slots) {
  if (table_slots == 0) table_slots = 1 << 16;
  // round to power of two
  uint32_t ts = 1;
  while (ts < table_slots) ts <<= 1;
  table_slots = ts;

  uint64_t header = align_up(sizeof(StoreHeader));
  uint64_t table = align_up(sizeof(Slot) * table_slots);
  uint64_t heap = align_up(capacity);
  uint64_t total = header + table + heap;

  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }

  auto* hdr = static_cast<StoreHeader*>(base);
  memset(hdr, 0, sizeof(StoreHeader));
  hdr->mapping_size = total;
  hdr->heap_offset = header + table;
  hdr->heap_size = heap;
  hdr->table_slots = table_slots;

  pthread_mutexattr_t mattr;
  pthread_mutexattr_init(&mattr);
  pthread_mutexattr_setpshared(&mattr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&mattr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mutex, &mattr);
  pthread_condattr_t cattr;
  pthread_condattr_init(&cattr);
  pthread_condattr_setpshared(&cattr, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&hdr->seal_cond, &cattr);

  memset(static_cast<uint8_t*>(base) + header, 0, table);

  // One big free block spanning the heap.
  auto* fb = reinterpret_cast<FreeBlock*>(static_cast<uint8_t*>(base) + hdr->heap_offset);
  fb->size = heap;
  fb->next_offset = 0;
  hdr->free_head = hdr->heap_offset;

  hdr->magic = kMagic;  // publish last

  // NOTE on first-touch cost: tmpfs pages are zero-filled on first write,
  // so the first put into a fresh region runs at page-fault speed; the
  // first-fit allocator reuses freed (already-faulted) blocks from the start
  // of the heap, so steady-state puts run at memcpy speed. A background
  // prefault thread was measured to hurt on small-core hosts (it competes
  // with the put for the same core); callers that want eager population can
  // use rtpu_store_prefault().

  Store* s = new Store();
  s->base = static_cast<uint8_t*>(base);
  s->size = total;
  s->hdr = hdr;
  s->slots = reinterpret_cast<Slot*>(s->base + header);
  return s;
}

void* rtpu_store_connect(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<StoreHeader*>(base);
  if (hdr->magic != kMagic) {
    munmap(base, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  Store* s = new Store();
  s->base = static_cast<uint8_t*>(base);
  s->size = static_cast<uint64_t>(st.st_size);
  s->hdr = hdr;
  s->slots = reinterpret_cast<Slot*>(s->base + align_up(sizeof(StoreHeader)));
  return s;
}

void rtpu_store_close(void* handle) {
  // Intentionally do NOT munmap: user code may still hold zero-copy numpy
  // views into the mapping (the same hazard exists with plasma in the
  // reference). The mapping is reclaimed at process exit; the backing file
  // is freed once the creator unlinks it and all mappings are gone.
  auto* s = static_cast<Store*>(handle);
  delete s;
}

// Eagerly populate the heap (MADV_POPULATE_WRITE is content-preserving and
// safe concurrently with puts). Blocking; call from a spare thread.
void rtpu_store_prefault(void* handle) {
#ifndef MADV_POPULATE_WRITE
#define MADV_POPULATE_WRITE 23
#endif
  auto* s = static_cast<Store*>(handle);
  uint8_t* p = s->base + s->hdr->heap_offset;
  uint64_t len = s->hdr->heap_size;
  // madvise requires a page-aligned address; heap_offset is only 64B-aligned.
  uintptr_t addr = reinterpret_cast<uintptr_t>(p);
  uintptr_t aligned = addr & ~static_cast<uintptr_t>(4095);
  len += addr - aligned;
  if (madvise(reinterpret_cast<void*>(aligned), len, MADV_POPULATE_WRITE) != 0) {
    // Fallback (old kernels / EINVAL): touch one byte per page with an
    // atomic OR of 0 — faults the page for write while preserving any value
    // a concurrent put may have stored there.
    for (uint64_t off = 0; off < len; off += 4096) {
      __atomic_fetch_or(reinterpret_cast<uint8_t*>(aligned + off), 0,
                        __ATOMIC_RELAXED);
    }
  }
}

void rtpu_store_destroy(const char* name) { shm_unlink(name); }

// ---------------------------------------------------------------- channels
//
// Seqno-gated mutable channels for compiled-DAG pipelines (capability
// analogue of the reference's mutable-object channels,
// src/ray/core_worker/experimental_mutable_object_manager.h). A channel is
// an ordinary sealed object whose payload starts with a ChanHeader: two
// monotonically increasing counters (seqno: writer publishes; ack: reader
// consumed) plus a PER-CHANNEL process-shared mutex+cond, so a post wakes
// only this channel's peer — never the whole store (a global cond turns a
// 3-stage pipeline into a context-switch storm on small hosts).

struct ChanHeader {
  uint64_t ctr[2];  // [0]=seqno, [1]=ack
  uint64_t len;     // payload length of the current message
  // parked-waiter count: a post only takes the mutex and broadcasts when
  // someone is actually parked on the cond. With a spinning (or absent)
  // peer a post is a pure release-store — no mutex, no futex wake — which
  // is what makes a hot pipelined hop syscall-free on BOTH sides. Field
  // sits after len so the Python side's len offset (16) is unchanged.
  uint64_t waiters;
  pthread_mutex_t mu;
  pthread_cond_t cv;
};

uint64_t rtpu_chan_header_size() { return sizeof(ChanHeader); }

static ChanHeader* chan_at(void* handle, uint64_t offset) {
  auto* s = static_cast<Store*>(handle);
  return reinterpret_cast<ChanHeader*>(s->base + offset);
}

int rtpu_chan_init(void* handle, uint64_t offset) {
  ChanHeader* c = chan_at(handle, offset);
  c->ctr[0] = c->ctr[1] = 0;
  c->len = 0;
  c->waiters = 0;
  pthread_mutexattr_t mattr;
  pthread_mutexattr_init(&mattr);
  pthread_mutexattr_setpshared(&mattr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&mattr, PTHREAD_MUTEX_ROBUST);
  if (pthread_mutex_init(&c->mu, &mattr) != 0) return -1;
  pthread_condattr_t cattr;
  pthread_condattr_init(&cattr);
  pthread_condattr_setpshared(&cattr, PTHREAD_PROCESS_SHARED);
  // deadlines come from timespec_in (CLOCK_REALTIME); the cond must use
  // the same clock or timedwait deadlines never fire
  if (pthread_cond_init(&c->cv, &cattr) != 0) return -1;
  return 0;
}

static void chan_lock(ChanHeader* c) {
  if (pthread_mutex_lock(&c->mu) == EOWNERDEAD)
    pthread_mutex_consistent(&c->mu);
}

uint64_t rtpu_chan_seqno(void* handle, uint64_t offset, int which) {
  ChanHeader* c = chan_at(handle, offset);
  uint64_t v;
  __atomic_load(&c->ctr[which], &v, __ATOMIC_ACQUIRE);
  return v;
}

// Publish: store the counter (payload writes become visible before it),
// then wake this channel's peer — but only if one is actually PARKED.
// seq_cst on the counter store and the waiters load pairs with seq_cst
// on the waiter's registration store and counter re-check (Dekker
// pattern): at least one side observes the other, so either the post
// sees waiters>0 and broadcasts under the mutex, or the waiter's
// re-check (done before parking, under the mutex) sees the new value.
// The waiter's 50ms timedwait backstop self-heals any residual miss.
void rtpu_chan_post(void* handle, uint64_t offset, int which,
                    uint64_t value) {
  ChanHeader* c = chan_at(handle, offset);
  __atomic_store(&c->ctr[which], &value, __ATOMIC_SEQ_CST);
  uint64_t w;
  __atomic_load(&c->waiters, &w, __ATOMIC_SEQ_CST);
  if (w == 0) return;  // spinning or absent peer: no futex round-trip
  chan_lock(c);
  pthread_cond_broadcast(&c->cv);
  pthread_mutex_unlock(&c->mu);
}

// Wait until counter `which` exceeds `last`. Returns the observed value,
// or 0 on timeout (counters start at 1).
uint64_t rtpu_chan_wait(void* handle, uint64_t offset, int which,
                        uint64_t last, int timeout_ms) {
  ChanHeader* c = chan_at(handle, offset);
  uint64_t v = rtpu_chan_seqno(handle, offset, which);
  if (v > last) return v;
  struct timespec deadline;
  if (timeout_ms > 0) timespec_in(&deadline, timeout_ms);
  chan_lock(c);
  // register as PARKED before the re-check: a post that misses this
  // increment happened before it, so the re-check below sees its value
  // (seq_cst pairing with rtpu_chan_post)
  __atomic_add_fetch(&c->waiters, 1, __ATOMIC_SEQ_CST);
  for (;;) {
    uint64_t u;
    __atomic_load(&c->ctr[which], &u, __ATOMIC_SEQ_CST);
    v = u;
    if (v > last) {
      __atomic_sub_fetch(&c->waiters, 1, __ATOMIC_SEQ_CST);
      pthread_mutex_unlock(&c->mu);
      return v;
    }
    if (timeout_ms == 0) {
      __atomic_sub_fetch(&c->waiters, 1, __ATOMIC_SEQ_CST);
      pthread_mutex_unlock(&c->mu);
      return 0;
    }
    // Bounded waits even for timeout<0: a post can slip between the
    // atomic check and the cond wait; a 50ms re-check caps that stall
    // (the seq_cst waiters handshake makes it near-impossible, this is
    // a backstop).
    struct timespec tick;
    timespec_in(&tick, 50);
    int rc = pthread_cond_timedwait(&c->cv, &c->mu,
                                    timeout_ms < 0 ? &tick : &deadline);
    if (rc == ETIMEDOUT && timeout_ms > 0) {
      v = rtpu_chan_seqno(handle, offset, which);
      __atomic_sub_fetch(&c->waiters, 1, __ATOMIC_SEQ_CST);
      pthread_mutex_unlock(&c->mu);
      return v > last ? v : 0;
    }
  }
}

// Adaptive spin-then-block wait: busy-poll the counter atomic for up to
// `spin_us` microseconds before falling back to the condvar path above.
// A pipelined hop whose peer posts within the budget costs a cache-line
// read instead of a futex sleep + wakeup + ~18us context switch. Each
// poll round does a short burst of CPU pause hints then sched_yield()s:
// on a single-core host the peer NEEDS this core to post the counter, so
// an unyielding spin would stall the very event it waits for — yield
// keeps the round-trip at scheduler-quantum cost, still well under the
// futex path. spin_us == 0 degenerates to rtpu_chan_wait exactly.
uint64_t rtpu_chan_wait_spin(void* handle, uint64_t offset, int which,
                             uint64_t last, int timeout_ms,
                             uint32_t spin_us) {
  ChanHeader* c = chan_at(handle, offset);
  uint64_t v;
  __atomic_load(&c->ctr[which], &v, __ATOMIC_ACQUIRE);
  if (v > last) return v;
  if (spin_us > 0 && timeout_ms != 0) {
    struct timespec start, now;
    clock_gettime(CLOCK_MONOTONIC, &start);
    const int64_t budget_ns = static_cast<int64_t>(spin_us) * 1000;
    for (;;) {
      for (int i = 0; i < 64; ++i) {
#if defined(__x86_64__) || defined(__i386__)
        __builtin_ia32_pause();
#elif defined(__aarch64__)
        asm volatile("yield" ::: "memory");
#endif
        __atomic_load(&c->ctr[which], &v, __ATOMIC_ACQUIRE);
        if (v > last) return v;
      }
      clock_gettime(CLOCK_MONOTONIC, &now);
      int64_t elapsed_ns =
          (now.tv_sec - start.tv_sec) * 1000000000LL +
          (now.tv_nsec - start.tv_nsec);
      if (elapsed_ns >= budget_ns) break;
      sched_yield();  // single-core: hand the peer the CPU to post
    }
  }
  return rtpu_chan_wait(handle, offset, which, last, timeout_ms);
}

uint8_t* rtpu_store_base(void* handle) { return static_cast<Store*>(handle)->base; }
uint64_t rtpu_store_mapping_size(void* handle) { return static_cast<Store*>(handle)->size; }

// Allocate an object of `size` bytes; returns payload offset (0 on failure).
// The object is CREATED (not yet visible to getters) until sealed.
uint64_t rtpu_store_create_object(void* handle, const uint8_t* id, uint64_t size) {
  auto* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_insert_slot(s, id);
  if (slot == nullptr) {
    unlock(s);
    return 0;  // table full or duplicate id
  }
  uint64_t off = heap_alloc(s, size);
  while (off == 0) {
    if (!evict_one(s)) break;
    off = heap_alloc(s, size);
  }
  if (off == 0) {
    unlock(s);
    return 0;
  }
  memcpy(slot->id, id, kIdLen);
  slot->state = SLOT_CREATED;
  slot->data_offset = off;
  slot->data_size = size;
  slot->refcount = 1;  // creator holds a reference until seal+release
  slot->lru_prev = slot->lru_next = 0;
  s->hdr->num_objects++;
  unlock(s);
  return off;
}

int rtpu_store_seal(void* handle, const uint8_t* id) {
  auto* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_slot(s, id, false);
  if (!slot || slot->state != SLOT_CREATED) {
    unlock(s);
    return -1;
  }
  slot->state = SLOT_SEALED;
  slot->refcount -= 1;  // drop creator ref
  if (slot->refcount == 0) lru_push_back(s, slot);
  pthread_cond_broadcast(&s->hdr->seal_cond);
  unlock(s);
  return 0;
}

// Seal keeping the creator reference (refcount stays >= 1). Used for the
// owner-handoff protocol: a task-return/put container is born referenced,
// and the owner process adopts that reference as its tracking pin — there
// is never a refcount==0 window in which the LRU could evict a live object.
int rtpu_store_seal_retain(void* handle, const uint8_t* id) {
  auto* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_slot(s, id, false);
  if (!slot || slot->state != SLOT_CREATED) {
    unlock(s);
    return -1;
  }
  slot->state = SLOT_SEALED;
  pthread_cond_broadcast(&s->hdr->seal_cond);
  unlock(s);
  return 0;
}

// Get: waits up to timeout_ms for the object to exist+seal. On success fills
// offset/size, bumps refcount (pinning it against eviction), returns 0.
// Returns -1 on timeout.
int rtpu_store_get(void* handle, const uint8_t* id, int timeout_ms,
                   uint64_t* offset, uint64_t* size) {
  auto* s = static_cast<Store*>(handle);
  struct timespec deadline;
  if (timeout_ms > 0) timespec_in(&deadline, timeout_ms);
  lock(s);
  for (;;) {
    Slot* slot = find_slot(s, id, false);
    if (slot && slot->state == SLOT_SEALED) {
      if (slot->refcount == 0) lru_unlink(s, slot);
      slot->refcount += 1;
      *offset = slot->data_offset;
      *size = slot->data_size;
      unlock(s);
      return 0;
    }
    if (timeout_ms == 0) {
      unlock(s);
      return -1;
    }
    int rc;
    if (timeout_ms < 0) {
      rc = pthread_cond_wait(&s->hdr->seal_cond, &s->hdr->mutex);
    } else {
      rc = pthread_cond_timedwait(&s->hdr->seal_cond, &s->hdr->mutex, &deadline);
    }
    if (rc == ETIMEDOUT) {
      unlock(s);
      return -1;
    }
  }
}

int rtpu_store_release(void* handle, const uint8_t* id) {
  auto* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_slot(s, id, false);
  if (!slot || slot->refcount <= 0) {
    unlock(s);
    return -1;
  }
  slot->refcount -= 1;
  if (slot->refcount == 0 && slot->state == SLOT_SEALED) lru_push_back(s, slot);
  unlock(s);
  return 0;
}

// Current refcount of an object (-1 if absent). Lets the owner identify
// objects only it references (safe to spill/delete: refcount == its pins).
int64_t rtpu_store_refcount(void* handle, const uint8_t* id) {
  auto* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_slot(s, id, false);
  int64_t r = slot ? slot->refcount : -1;
  unlock(s);
  return r;
}

int rtpu_store_contains(void* handle, const uint8_t* id) {
  auto* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_slot(s, id, false);
  int r = (slot && slot->state == SLOT_SEALED) ? 1 : 0;
  unlock(s);
  return r;
}

// Explicit delete (out-of-band ref-count driven, from the owner). Frees now if
// unreferenced, else marks for eviction at refcount 0 (here: just LRU'd).
int rtpu_store_delete(void* handle, const uint8_t* id) {
  auto* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_slot(s, id, false);
  if (!slot) {
    unlock(s);
    return -1;
  }
  if (slot->refcount == 0) {
    if (slot->state == SLOT_SEALED) lru_unlink(s, slot);
    heap_free(s, slot->data_offset, align_up(slot->data_size));
    s->hdr->bytes_in_use -= align_up(slot->data_size);
    s->hdr->num_objects--;
    slot->state = SLOT_TOMBSTONE;
  }
  // else: pinned; it will fall into LRU when released and evict under pressure.
  unlock(s);
  return 0;
}

void rtpu_store_stats(void* handle, uint64_t* heap_size, uint64_t* bytes_in_use,
                      uint64_t* num_objects, uint64_t* evictions) {
  auto* s = static_cast<Store*>(handle);
  lock(s);
  *heap_size = s->hdr->heap_size;
  *bytes_in_use = s->hdr->bytes_in_use;
  *num_objects = s->hdr->num_objects;
  *evictions = s->hdr->evictions;
  unlock(s);
}

}  // extern "C"
