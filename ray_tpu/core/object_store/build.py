"""Compile the shm store C++ extension on first use (cached by mtime)."""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_shm_store.cc")
_LIB = os.path.join(_DIR, "_shm_store.so")
_lock = threading.Lock()


def ensure_built() -> str:
    """Build _shm_store.so if missing or stale; return its path."""
    with _lock:
        if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
            return _LIB
        tmp = _LIB + f".tmp{os.getpid()}"
        cmd = [
            "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
            "-o", tmp, _SRC, "-lpthread", "-lrt",
        ]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, _LIB)
        return _LIB
