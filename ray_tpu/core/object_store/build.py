"""Compile the shm store C++ extension on first use (cached by mtime).

``python -m ray_tpu.core.object_store.build --sanitize=thread`` (or
``address``) builds a sanitizer-instrumented variant next to the normal
one; the stress harness (tests/test_store_sanitize.py) loads it via
RTPU_STORE_LIB (reference practice: TSAN/ASAN CI jobs over the plasma
store, SURVEY §4.3)."""

from __future__ import annotations

import os
import subprocess
import threading

from ray_tpu.util.debug_lock import make_lock

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_shm_store.cc")
_LIB = os.path.join(_DIR, "_shm_store.so")
_lock = make_lock("object_store.build._lock")

_SAN_FLAGS = {
    "thread": ["-fsanitize=thread", "-O1", "-g"],
    "address": ["-fsanitize=address", "-O1", "-g",
                "-fno-omit-frame-pointer"],
}


def _compile(out: str, extra: list) -> None:
    tmp = out + f".tmp{os.getpid()}"
    cmd = (["g++", "-std=c++17", "-shared", "-fPIC"] + extra
           + ["-o", tmp, _SRC, "-lpthread", "-lrt"])
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, out)


def ensure_built(sanitize: str = "", force: bool = False) -> str:
    """Build the store library if missing or stale; return its path.

    ``sanitize`` in {"thread", "address"} builds/returns the
    instrumented variant (separate .so — normal users never pay the
    sanitizer tax). ``force`` recompiles even when the cached binary
    looks fresh — the loader uses it when a prebuilt .so turns out to
    be ABI-incompatible with the host (e.g. built against a newer
    glibc than the one present)."""
    if sanitize:
        lib = os.path.join(_DIR, f"_shm_store_{sanitize}.so")
        flags = _SAN_FLAGS[sanitize]
    else:
        lib, flags = _LIB, ["-O2"]
    with _lock:
        if not force and os.path.exists(lib) and \
                os.path.getmtime(lib) >= os.path.getmtime(_SRC):
            return lib
        _compile(lib, flags)
        return lib


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--sanitize", choices=["thread", "address", ""],
                    default="")
    path = ensure_built(ap.parse_args().sanitize)
    print(path)
