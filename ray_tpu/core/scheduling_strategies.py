"""Scheduling strategies (reference: python/ray/util/scheduling_strategies.py).

On the single-node runtime these mostly affect resource accounting (which
pool a task/actor draws from); the node-selection semantics activate with
the multi-node control plane.
"""

from __future__ import annotations

from typing import Optional


class PlacementGroupSchedulingStrategy:
    """Schedule into a placement group bundle's reserved resources."""

    def __init__(self, placement_group, placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks

    def _to_wire(self):
        return ("pg", self.placement_group.id.binary(),
                self.placement_group_bundle_index)


class NodeAffinitySchedulingStrategy:
    """Pin to a node (single-node: validated, then trivial)."""

    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft

    def _to_wire(self):
        return ("node", self.node_id, self.soft)


# String strategies "DEFAULT" and "SPREAD" are accepted as-is.
