"""Placement groups: gang resource reservation with TPU-topology awareness.

Reference behavior: python/ray/util/placement_group.py:145 (API),
gcs_placement_group_manager.h:230 (lifecycle) and the bundle policies
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD
(raylet/scheduling/policy/bundle_scheduling_policy.h:31).

TPU-native addition: a bundle requesting ``{"TPU": n}`` is bound to concrete
chips of the node's slice; STRICT_PACK demands one ICI-contiguous rectangle
covering the whole group (the shape a mesh program wants), PACK tries
per-bundle contiguity, SPREAD distributes bundles across hosts.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.core.resources import ResourceSet
from ray_tpu.exceptions import PlacementGroupError

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class Bundle:
    """One reserved resource bundle inside a PG."""

    __slots__ = ("index", "spec", "reserved", "consumed", "chips", "free_chips")

    def __init__(self, index: int, spec: Dict[str, float]):
        self.index = index
        self.spec = dict(spec)
        self.reserved = ResourceSet(spec)
        self.consumed = ResourceSet()
        self.chips: List[int] = []       # concrete TPU chip indices, if any
        self.free_chips: List[int] = []  # not yet assigned to an actor/task

    def take_chips(self, n: int) -> List[int]:
        taken, self.free_chips = self.free_chips[:n], self.free_chips[n:]
        return taken

    def return_chips(self, chips: List[int]):
        self.free_chips.extend(chips)

    def can_fit(self, req: ResourceSet) -> bool:
        return (self.consumed + req).is_subset_of(self.reserved)

    def acquire(self, req: ResourceSet):
        if not self.can_fit(req):
            raise PlacementGroupError(
                f"bundle {self.index} cannot fit {req.to_dict()} "
                f"(reserved={self.reserved.to_dict()}, "
                f"used={self.consumed.to_dict()})"
            )
        self.consumed = self.consumed + req

    def release(self, req: ResourceSet):
        self.consumed = self.consumed - req


class PlacementGroupState:
    """Driver-side state for one PG."""

    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str, name: Optional[str]):
        self.id = pg_id
        self.strategy = strategy
        self.name = name
        self.bundles = [Bundle(i, b) for i, b in enumerate(bundles)]
        self.ready_event = threading.Event()
        self.removed = False
        self.infeasible_reason: Optional[str] = None

    def total_request(self) -> ResourceSet:
        total = ResourceSet()
        for b in self.bundles:
            total = total + b.reserved
        return total

    def find_bundle(self, req: ResourceSet, index: int = -1) -> Optional[Bundle]:
        if index >= len(self.bundles):
            return None
        if index >= 0:
            b = self.bundles[index]
            return b if b.can_fit(req) else None
        for b in self.bundles:
            if b.can_fit(req):
                return b
        return None


class PlacementGroup:
    """User-facing handle (serializable)."""

    def __init__(self, pg_id: PlacementGroupID,
                 bundles: Optional[List[Dict[str, float]]] = None):
        self._id = pg_id
        self._bundles = bundles or []

    @property
    def id(self) -> PlacementGroupID:
        return self._id

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self._bundles)

    @property
    def bundle_count(self) -> int:
        return len(self._bundles)

    def ready(self):
        """ObjectRef resolving to True once all bundles are reserved
        (reference: PlacementGroup.ready(), util/placement_group.py:74)."""
        from ray_tpu.core import runtime_context

        core = runtime_context.get_core()
        return core.placement_group_ready_ref(self._id)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        from ray_tpu.core import runtime_context

        core = runtime_context.get_core()
        return core.wait_placement_group(self._id, timeout_seconds)

    def chips_for_bundle(self, index: int) -> List[int]:
        """Concrete TPU chip indices bound to a bundle (TPU-native API)."""
        from ray_tpu.core import runtime_context

        core = runtime_context.get_core()
        return core.placement_group_chips(self._id, index)

    def __reduce__(self):
        return (PlacementGroup, (self._id, self._bundles))

    def __repr__(self):
        return f"PlacementGroup({self._id.hex()[:12]}, {len(self._bundles)} bundles)"


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: Optional[str] = None, lifetime: Optional[str] = None
                    ) -> PlacementGroup:
    """Reserve a gang of resource bundles.

    Mirrors ray.util.placement_group (util/placement_group.py:145).
    """
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}"
        )
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    for b in bundles:
        if not b or any(v <= 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b!r}")
    from ray_tpu.core import runtime_context

    core = runtime_context.get_core()
    return core.create_placement_group(bundles, strategy, name)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu.core import runtime_context

    runtime_context.get_core().remove_placement_group(pg.id)


def placement_group_table() -> Dict[str, dict]:
    from ray_tpu.core import runtime_context

    return runtime_context.get_core().placement_group_table()
