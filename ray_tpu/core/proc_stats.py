"""Per-process resource stats from /proc — no psutil in the image.

Reference: dashboard/modules/reporter/reporter_agent.py:428 collects
per-worker CPU/RSS via psutil; here the same numbers come straight from
/proc/<pid>/stat (utime+stime jiffies) and /proc/<pid>/status (VmRSS).
CPU percent is a delta between successive samples, so callers keep a
_CpuTracker per polling context.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100


def stack_dump_path(pid: int) -> str:
    """The one place the worker stack-dump path is defined (the SIGUSR1
    handler writes it, the collector reads it)."""
    return f"/tmp/rtpu_stack_{pid}.txt"


def sample_pid(pid: int) -> Optional[Dict[str, float]]:
    """{'cpu_jiffies', 'rss_bytes', 'num_threads'} or None if gone."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            parts = f.read().rsplit(b") ", 1)[1].split()
        # post-comm fields: index 11/12 are utime/stime, 17 num_threads
        utime, stime = int(parts[11]), int(parts[12])
        threads = int(parts[17])
        rss = 0
        with open(f"/proc/{pid}/status", "rb") as f:
            for line in f:
                if line.startswith(b"VmRSS:"):
                    rss = int(line.split()[1]) * 1024
                    break
        return {"cpu_jiffies": float(utime + stime),
                "rss_bytes": float(rss), "num_threads": float(threads)}
    except (OSError, ValueError, IndexError):
        return None


class CpuTracker:
    """Turns successive jiffy samples into cpu_percent per pid."""

    def __init__(self):
        self._last: Dict[int, tuple] = {}

    def prune(self, live_pids) -> None:
        """Drop samples for exited workers — a recycled pid must never
        diff against the dead process's jiffies."""
        live = set(live_pids)
        for pid in list(self._last):
            if pid not in live:
                del self._last[pid]

    def stats(self, pid: int) -> Optional[Dict[str, float]]:
        s = sample_pid(pid)
        if s is None:
            self._last.pop(pid, None)
            return None
        now = time.monotonic()
        prev = self._last.get(pid)
        self._last[pid] = (now, s["cpu_jiffies"])
        cpu_pct = 0.0
        if prev is not None and now > prev[0]:
            cpu_pct = ((s["cpu_jiffies"] - prev[1]) / _CLK_TCK
                       / (now - prev[0]) * 100.0)
        return {"cpu_percent": round(cpu_pct, 2),
                "rss_bytes": int(s["rss_bytes"]),
                "num_threads": int(s["num_threads"])}
