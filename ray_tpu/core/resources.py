"""Resource model with first-class TPU topology.

The reference models resources as fixed-point scalar maps
(src/ray/common/scheduling/cluster_resource_data.h, fixed_point.h) and bolts
TPU awareness on via custom resources emitted by an accelerator manager
(python/ray/_private/accelerators/tpu.py:71 — chip detection :49, pod-type
:198, "TPU-<pod_type>-head" gang resource :232). Here the slice/host/chip
topology IS the core resource model: a node owns a ``TpuSliceTopology`` and
chip allocation is topology-aware (contiguous sub-grids ride the ICI mesh).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# Fixed-point arithmetic: resources are stored as integers scaled by 1e4
# (the reference uses the same trick to avoid float drift in admission
# control — src/ray/common/scheduling/fixed_point.h).
RESOLUTION = 10_000


def to_fixed(v: float) -> int:
    return int(round(v * RESOLUTION))


def from_fixed(v: int) -> float:
    return v / RESOLUTION


class ResourceSet:
    """A non-negative resource vector keyed by resource name."""

    __slots__ = ("_r",)

    def __init__(self, resources: Optional[Dict[str, float]] = None):
        self._r: Dict[str, int] = {}
        if resources:
            for k, v in resources.items():
                fv = to_fixed(v)
                if fv < 0:
                    raise ValueError(f"negative resource {k}={v}")
                if fv:
                    self._r[k] = fv

    @classmethod
    def _from_fixed_map(cls, m: Dict[str, int]) -> "ResourceSet":
        rs = cls()
        rs._r = {k: v for k, v in m.items() if v}
        return rs

    def get(self, name: str) -> float:
        return from_fixed(self._r.get(name, 0))

    def to_dict(self) -> Dict[str, float]:
        return {k: from_fixed(v) for k, v in self._r.items()}

    def is_subset_of(self, other: "ResourceSet") -> bool:
        return all(other._r.get(k, 0) >= v for k, v in self._r.items())

    def __add__(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._r)
        for k, v in other._r.items():
            out[k] = out.get(k, 0) + v
        return ResourceSet._from_fixed_map(out)

    def subtract_unchecked(self, other: "ResourceSet") -> "ResourceSet":
        """Subtraction that may go negative (oversubscription debt while a
        blocked worker resumes — the reference raylet does the same when
        workers blocked in ray.get are released and re-admitted)."""
        out = dict(self._r)
        for k, v in other._r.items():
            out[k] = out.get(k, 0) - v
        return ResourceSet._from_fixed_map(out)

    def __sub__(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._r)
        for k, v in other._r.items():
            nv = out.get(k, 0) - v
            if nv < 0:
                raise ValueError(
                    f"resource {k} would go negative ({from_fixed(nv)})"
                )
            out[k] = nv
        return ResourceSet._from_fixed_map(out)

    def __bool__(self):
        return bool(self._r)

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._r == other._r

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"


# --------------------------------------------------------------------------
# TPU topology
# --------------------------------------------------------------------------

# (chips_per_host, default grid) for known generations; grids are the
# physical ICI meshes. v5e hosts have 4 chips in a 2x2; v5p 4 chips with 3D
# torus links. We model a slice as a logical 2D grid of chips for adjacency.
_GENERATION_CHIPS_PER_HOST = {
    "v2": 4, "v3": 4, "v4": 4, "v5e": 4, "v5litepod": 4, "v5p": 4, "v6e": 4,
}


def _grid_for(num_chips: int) -> Tuple[int, int]:
    """Most-square 2D grid for n chips (ICI mesh model)."""
    best = (1, num_chips)
    d = 1
    while d * d <= num_chips:
        if num_chips % d == 0:
            best = (d, num_chips // d)
        d += 1
    return best


@dataclass(frozen=True)
class TpuChip:
    """One chip's position in the slice."""

    index: int
    host: int
    x: int
    y: int


class TpuSliceTopology:
    """A TPU slice: generation, pod type, hosts × chips, 2D ICI grid.

    The allocation primitive is *contiguous rectangles* of the chip grid —
    gang placements that ride ICI links only (the property STRICT_PACK
    bundles want). Mirrors what the reference derives from GCE metadata
    (accelerators/tpu.py:198 pod type, :232 worker count) but as a core
    scheduler structure instead of opaque custom resources.
    """

    def __init__(self, generation: str = "v5e", num_chips: int = 1,
                 chips_per_host: Optional[int] = None):
        self.generation = generation
        self.num_chips = num_chips
        self.chips_per_host = chips_per_host or min(
            num_chips, _GENERATION_CHIPS_PER_HOST.get(generation, 4)
        )
        self.num_hosts = max(1, num_chips // self.chips_per_host)
        self.pod_type = f"{generation}-{num_chips}"
        self.grid = _grid_for(num_chips)
        gx, gy = self.grid
        self.chips: List[TpuChip] = [
            TpuChip(index=i, host=i // self.chips_per_host, x=i % gx, y=i // gx)
            for i in range(num_chips)
        ]
        self._free = set(range(num_chips))

    # -- detection ----------------------------------------------------------

    @classmethod
    def detect(cls) -> Optional["TpuSliceTopology"]:
        """Detect local TPU chips.

        Order: explicit env override (RTPU_TPU_TOPOLOGY=v5e-8), TPU chip
        device files (/dev/accel* or /dev/vfio — same signals the reference
        scans, accelerators/tpu.py:49), else a jax probe is skipped (too
        slow for init); no TPU → None.
        """
        override = os.environ.get("RTPU_TPU_TOPOLOGY")
        if override:
            gen, _, n = override.rpartition("-")
            return cls(generation=gen or "v5e", num_chips=int(n))
        try:
            import glob

            accel = glob.glob("/dev/accel*")
            if not accel:
                # vfio-backed TPU VMs: group nodes are numeric; skip the
                # /dev/vfio/vfio control node (and non-TPU vfio hosts are
                # excluded by requiring the TPU env marker).
                groups = [p for p in glob.glob("/dev/vfio/*")
                          if os.path.basename(p).isdigit()]
                if groups and os.environ.get("TPU_SKIP_MDS_QUERY") is not None:
                    accel = groups
            if accel:
                return cls(generation="v5e", num_chips=len(accel))
        except OSError:
            pass
        if os.environ.get("RTPU_ASSUME_TPU"):
            return cls(generation="v5e", num_chips=1)
        return None

    # -- allocation ---------------------------------------------------------

    def available_chips(self) -> int:
        return len(self._free)

    def allocate(self, n: int, contiguous: bool = True) -> Optional[List[int]]:
        """Allocate n chips; contiguous=True demands an ICI-adjacent
        rectangle (returns None if impossible)."""
        if n > len(self._free):
            return None
        if not contiguous or n == 1:
            picked = sorted(self._free)[:n]
            for c in picked:
                self._free.discard(c)
            return picked
        rect = self._find_rect(n)
        if rect is None:
            return None
        for c in rect:
            self._free.discard(c)
        return rect

    def _find_rect(self, n: int) -> Optional[List[int]]:
        gx, gy = self.grid
        # candidate rectangle shapes, squarest first
        shapes = []
        for w in range(1, gx + 1):
            if n % w == 0 and n // w <= gy:
                shapes.append((w, n // w))
        shapes.sort(key=lambda s: abs(s[0] - s[1]))
        by_pos = {(c.x, c.y): c.index for c in self.chips}
        for w, h in shapes:
            for oy in range(gy - h + 1):
                for ox in range(gx - w + 1):
                    cells = [
                        by_pos[(ox + dx, oy + dy)]
                        for dy in range(h)
                        for dx in range(w)
                    ]
                    if all(c in self._free for c in cells):
                        return cells
        return None

    def release(self, chips: List[int]):
        for c in chips:
            if 0 <= c < self.num_chips:
                self._free.add(c)

    def __repr__(self):
        return (f"TpuSliceTopology({self.pod_type}, grid={self.grid}, "
                f"free={len(self._free)}/{self.num_chips})")


def node_resources(num_cpus: Optional[int] = None,
                   topology: Optional[TpuSliceTopology] = None,
                   object_store_memory: int = 0) -> Dict[str, float]:
    """Total resource vector for a node (reference emits the same shape:
    CPU/TPU/memory + 'TPU-<pod>-head' for slice gang scheduling)."""
    r: Dict[str, float] = {"CPU": float(num_cpus or os.cpu_count() or 1)}
    if object_store_memory:
        r["object_store_memory"] = float(object_store_memory)
    if topology is not None:
        r["TPU"] = float(topology.num_chips)
        r[f"TPU-{topology.pod_type}-head"] = 1.0
    return r
