"""Entry-point shim: ``python -m ray_tpu.core.worker_main``.

Kept separate from the implementation so that classes defined in the worker
module are never duplicated between ``__main__`` and the canonical module
path (which would break isinstance checks on unpickled objects).
"""

from ray_tpu.core.worker_proc import main

if __name__ == "__main__":
    main()
