"""Entry-point shim: ``python -m ray_tpu.core.worker_main [--zygote]``.

Kept separate from the implementation so that classes defined in the worker
module are never duplicated between ``__main__`` and the canonical module
path (which would break isinstance checks on unpickled objects).

``--zygote`` starts the pre-warmed fork template instead of a worker
(reference: prestarted workers, src/ray/raylet/worker_pool.h:344).
"""

import sys

from ray_tpu.core.worker_proc import main, zygote_main

if __name__ == "__main__":
    if "--zygote" in sys.argv[1:]:
        zygote_main()
    else:
        main()
