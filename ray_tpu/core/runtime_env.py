"""Runtime environments: ship code to workers (working_dir / py_modules).

Reference: python/ray/_private/runtime_env/ — there, working_dir and
py_modules are zipped, uploaded to the GCS package store, downloaded and
extracted by each node's runtime-env agent, then applied per worker
(chdir + sys.path). Here the same shape without a separate agent:

- driver: `prepare(core, runtime_env)` zips each local path into a
  content-addressed package and registers the bytes with the core
  (local runtime: in-process table; cluster: GCS KV `pkg:<hash>`),
  rewriting the env to hash references — the env dict that travels with
  the task/actor is small and serializable.
- worker: `apply(runtime_env, core)` fetches packages it doesn't have
  (REQ_PKG to its core, answered from the table / GCS KV), extracts them
  once into the session package cache, then chdirs into the working_dir
  and prepends py_modules to sys.path. Per-task application is restored
  after the task; actor-scoped application persists for the actor's
  lifetime (the worker is dedicated to it).

pip environments (reference: _private/runtime_env/pip.py) install into
a per-requirements-hash virtualenv (--system-site-packages) created
lazily node-side by the first worker that needs it. Tasks/actors pinned
to a pip env run on PER-ENV WORKER PROCESSES launched with the venv's
OWN interpreter (core/runtime.py env-keyed pools — the reference's
worker_pool.h:153 design): module versions are truly isolated, because
an env worker never imports outside its venv's resolution order and a
pooled worker never imports from a venv. The sys.path-activation path
below remains only for foreign-env application (a worker of env A told
to run env B — possible through nested submissions), where the
documented already-imported-module caveat still applies.
conda/image_uri isolation has a pluggable design: an ``EnvProvider``
maps a runtime_env kind to the interpreter its dedicated workers exec
(register_env_provider); pip ships built-in, conda/container providers
plug in where the host supplies the environment runtime (nothing
installable in this image — using those kinds without a provider is a
loud gated error, tested with a stub provider).
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from typing import Callable, Dict, Optional, Tuple

_EXCLUDE_DIRS = {".git", "__pycache__", ".venv", "node_modules", ".eggs"}
_EXCLUDE_SUFFIXES = (".pyc", ".pyo")
_MAX_PACKAGE_BYTES = 512 << 20


def _iter_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _EXCLUDE_DIRS)
        for f in sorted(filenames):
            if f.endswith(_EXCLUDE_SUFFIXES):
                continue
            full = os.path.join(dirpath, f)
            yield os.path.relpath(full, root), full


def package_path(path: str, *, prefix: str = "") -> Tuple[str, bytes]:
    """Zip a directory (or single .py file) deterministically.

    Returns (content_hash, zip_bytes). The hash covers names + contents
    (not zip metadata), so identical trees share a package.
    """
    path = os.path.abspath(path)
    h = hashlib.sha256()
    entries = []
    if os.path.isfile(path):
        entries = [(os.path.basename(path), path)]
    elif os.path.isdir(path):
        entries = [(os.path.join(prefix, rel) if prefix else rel, full)
                   for rel, full in _iter_files(path)]
    else:
        raise FileNotFoundError(f"runtime_env path {path!r} does not exist")
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for arcname, full in entries:
            with open(full, "rb") as f:
                data = f.read()
            total += len(data)
            if total > _MAX_PACKAGE_BYTES:
                raise ValueError(
                    f"runtime_env package {path!r} exceeds "
                    f"{_MAX_PACKAGE_BYTES >> 20} MiB")
            h.update(arcname.encode())
            h.update(b"\0")
            h.update(data)
            info = zipfile.ZipInfo(arcname, date_time=(1980, 1, 1, 0, 0, 0))
            zf.writestr(info, data)
    return h.hexdigest()[:32], buf.getvalue()


def _tree_signature(path: str):
    """Cheap change detector: (file count, max mtime_ns, total bytes).
    Walking stats is ~100x cheaper than re-reading + zipping the tree on
    every .remote() call."""
    if os.path.isfile(path):
        st = os.stat(path)
        return (1, st.st_mtime_ns, st.st_size)
    n = mt = size = 0
    for _, full in _iter_files(path):
        st = os.stat(full)
        n += 1
        mt = max(mt, st.st_mtime_ns)
        size += st.st_size
    return (n, mt, size)


def _package_cached(core, path: str, *, prefix: str = "") -> str:
    """Package + register once per unchanged tree; returns the hash."""
    cache = getattr(core, "_renv_cache", None)
    if cache is None:
        cache = core._renv_cache = {}
    key = (os.path.abspath(path), prefix)
    sig = _tree_signature(os.path.abspath(path))
    hit = cache.get(key)
    if hit and hit[0] == sig:
        return hit[1]
    h, data = package_path(path, prefix=prefix)
    core.register_package(h, data)
    cache[key] = (sig, h)
    return h


def prepare(core, runtime_env: Optional[dict]) -> Optional[dict]:
    """Driver-side: package local paths, register bytes with the core,
    rewrite the env to content-hash references."""
    if not runtime_env:
        return runtime_env
    if "working_dir_pkg" in runtime_env or "py_modules_pkgs" in runtime_env:
        return runtime_env  # already prepared (e.g. re-submission)
    out = dict(runtime_env)
    wd = out.pop("working_dir", None)
    if wd is not None:
        out["working_dir_pkg"] = _package_cached(core, wd)
    mods = out.pop("py_modules", None)
    if mods:
        hashes = []
        for m in mods:
            m = os.path.abspath(m)
            # a module DIRECTORY must stay importable after extraction:
            # nest it under its own name so sys.path points at the parent
            prefix = os.path.basename(m.rstrip(os.sep)) \
                if os.path.isdir(m) else ""
            hashes.append(_package_cached(core, m, prefix=prefix))
        out["py_modules_pkgs"] = hashes
    return out


def ensure_extracted(cache_root: str, pkg_hash: str,
                     fetch: Callable[[str], bytes]) -> str:
    """Extract package ``pkg_hash`` under the cache once; returns its dir.
    Atomic against concurrent workers (extract to temp + rename)."""
    dest = os.path.join(cache_root, pkg_hash)
    if os.path.isdir(dest):
        return dest
    data = fetch(pkg_hash)
    if data is None:
        raise FileNotFoundError(
            f"runtime_env package {pkg_hash} not found in the package "
            "store (was it registered by the submitting driver?)")
    tmp = f"{dest}.tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(data)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, dest)
    except OSError:
        # another worker won the race; ours is redundant
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def _pip_env_key(packages, options) -> str:
    blob = "\n".join(sorted(packages)) + "\0" + " ".join(options)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def normalize_pip(spec) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    """runtime_env["pip"] forms: a list of requirement strings, or a
    dict {"packages": [...], "pip_install_options": [...]}."""
    if isinstance(spec, (list, tuple)):
        return tuple(str(p) for p in spec), ()
    if isinstance(spec, dict):
        return (tuple(str(p) for p in spec.get("packages") or ()),
                tuple(str(o) for o in
                      spec.get("pip_install_options") or ()))
    raise ValueError(
        f"runtime_env['pip'] must be a list of requirements or a dict "
        f"with 'packages'; got {type(spec).__name__}")


def ensure_pip_env(cache_root: str, packages, options) -> str:
    """Create (once per node+requirements hash) a virtualenv with the
    requested packages; returns its site-packages dir.

    Concurrency: installers compete for an O_EXCL lock file carrying the
    holder's pid; the .done marker caches success. A SIGKILLed holder's
    lock is broken by renaming it aside (atomic election) — the breaker
    then LOOPS BACK to compete for a fresh lock like everyone else, so
    dest is only ever rebuilt by a process that holds the lock (no
    window where a breaker can rmtree a new installer's in-progress
    venv)."""
    import glob
    import shutil
    import subprocess
    import time

    key = _pip_env_key(packages, options)
    pip_root = os.path.join(cache_root, "pip")
    dest = os.path.join(pip_root, key)
    done = os.path.join(dest, ".done")
    lock = os.path.join(pip_root, f"{key}.lock")
    os.makedirs(pip_root, exist_ok=True)

    def site_packages() -> str:
        hits = glob.glob(os.path.join(dest, "lib", "python*",
                                      "site-packages"))
        if not hits:
            raise FileNotFoundError(f"pip env {key} has no site-packages")
        return hits[0]

    def lock_holder_dead(path) -> bool:
        """True when the pid written into the lock file no longer runs —
        a SIGKILLed installer must not brick this env forever."""
        try:
            pid = int(open(path).read().strip() or 0)
        except OSError:
            return False  # already reclaimed by a competing breaker
        except ValueError:
            pid = 0
        if pid <= 0:
            # empty/garbled lock: the installer died between O_EXCL
            # create and writing its pid. Mid-write is indistinguishable,
            # so require the file to be old enough that any live writer
            # would long since have finished its two-line write.
            try:
                return time.time() - os.path.getmtime(path) > 30.0
            except OSError:
                return False
        try:
            os.kill(pid, 0)
            return False
        except ProcessLookupError:
            return True
        except PermissionError:
            return False

    deadline = time.monotonic() + 600
    while True:
        if os.path.exists(done):
            return site_packages()
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            if lock_holder_dead(lock):
                # atomic rename elects ONE breaker; it merely clears the
                # dead lock and loops back to compete — dest is touched
                # only under a held lock
                stale = f"{lock}.stale.{os.getpid()}"
                try:
                    os.rename(lock, stale)
                    os.remove(stale)
                except OSError:
                    pass
                continue
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"pip env {key} install did not finish within 600s "
                    f"(holder of {lock} may be stuck)")
            time.sleep(0.2)
            continue
        try:
            os.write(fd, str(os.getpid()).encode())
            if os.path.exists(done):
                return site_packages()
            # a previous holder may have died mid-install: rebuild from
            # scratch (we hold the lock, nobody else is writing here)
            shutil.rmtree(dest, ignore_errors=True)
            subprocess.run(
                [sys.executable, "-m", "venv", "--system-site-packages",
                 dest], check=True, capture_output=True)
            py = os.path.join(dest, "bin", "python")
            # --system-site-packages resolves to the BASE prefix; when
            # THIS interpreter is itself a venv (common in container
            # images), its own site-packages — the framework's deps —
            # would be invisible to env workers running <venv>/bin/python.
            # Link every parent site dir via a .pth: processed after the
            # env's own site-packages dir, so env-pinned versions still
            # win.
            parents = [p for p in sys.path
                       if p.endswith(("site-packages", "dist-packages"))
                       and os.path.isdir(p)]
            if parents:
                for sp_dir in glob.glob(os.path.join(
                        dest, "lib", "python*", "site-packages")):
                    with open(os.path.join(
                            sp_dir, "_rtpu_parent_paths.pth"), "w") as f:
                        f.write("\n".join(parents) + "\n")
            proc = subprocess.run(
                [py, "-m", "pip", "install", "--disable-pip-version-check",
                 *options, *packages],
                capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"pip install failed for runtime_env packages "
                    f"{list(packages)}:\n{proc.stderr[-2000:]}")
            with open(done, "w") as f:
                f.write("\n".join(packages))
            return site_packages()
        finally:
            os.close(fd)
            try:
                os.remove(lock)
            except OSError:
                pass


def apply(runtime_env: Optional[dict], fetch: Callable[[str], bytes],
          cache_root: Optional[str] = None,
          own_pip_key: Optional[str] = None):
    """Worker-side: apply env_vars, working_dir, py_modules.

    ``own_pip_key``: the pip-env key this worker's interpreter IS (env
    workers run their venv's python). A task pinned to the same env
    needs no sys.path surgery or post-task module purge — that is the
    point of per-env worker processes.

    Returns opaque state for ``restore`` (None when nothing applied).
    """
    if not runtime_env:
        return None
    if "working_dir" in runtime_env or "py_modules" in runtime_env:
        # raw paths mean prepare() never ran (e.g. a core without
        # prepare_runtime_env support): fail loudly, not silently
        raise ValueError(
            "runtime_env working_dir/py_modules were not prepared by the "
            "submitting process — submit from a driver or a worker core "
            "with prepare_runtime_env support")
    cache_root = cache_root or os.environ.get(
        "RTPU_PKG_DIR", "/tmp/ray_tpu_pkgs")
    os.makedirs(cache_root, exist_ok=True)
    saved_env = None
    saved_cwd = None
    saved_path: Optional[list] = None
    pip_sp: Optional[str] = None
    try:
        env_vars = runtime_env.get("env_vars")
        if env_vars:
            saved_env = {k: os.environ.get(k) for k in env_vars}
            os.environ.update({k: str(v) for k, v in env_vars.items()})
        wd_hash = runtime_env.get("working_dir_pkg")
        mod_hashes = runtime_env.get("py_modules_pkgs") or []
        pip_spec = runtime_env.get("pip")
        if wd_hash or mod_hashes or pip_spec:
            saved_path = list(sys.path)
        if pip_spec:
            packages, options = normalize_pip(pip_spec)
            key = f"pip:{_pip_env_key(packages, options)}"
            if packages and key != own_pip_key:
                pip_sp = ensure_pip_env(cache_root, packages, options)
                sys.path.insert(0, pip_sp)
        if wd_hash:
            wd = ensure_extracted(cache_root, wd_hash, fetch)
            saved_cwd = os.getcwd()
            os.chdir(wd)
            sys.path.insert(0, wd)
        for h in mod_hashes:
            sys.path.insert(0, ensure_extracted(cache_root, h, fetch))
    except BaseException:
        # half-applied env must not leak into the pooled worker (e.g.
        # env_vars applied, then the pip install fails)
        restore((saved_env, saved_cwd, saved_path, pip_sp))
        raise
    if (saved_env is None and saved_cwd is None and saved_path is None
            and pip_sp is None):
        return None
    return (saved_env, saved_cwd, saved_path, pip_sp)


def restore(state) -> None:
    if state is None:
        return
    saved_env, saved_cwd, saved_path, pip_sp = state
    if pip_sp:
        # sys.path restore alone is not isolation: modules already
        # imported from the env's site-packages live on in sys.modules
        # and would satisfy env-less imports on this pooled worker
        prefix = pip_sp + os.sep
        for name, mod in list(sys.modules.items()):
            f = getattr(mod, "__file__", None)
            if f and f.startswith(prefix):
                del sys.modules[name]
                continue
            paths = getattr(mod, "__path__", None)
            if paths is not None:
                try:
                    if any(str(p).startswith(prefix) for p in paths):
                        del sys.modules[name]
                except TypeError:
                    pass
    if saved_env:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    if saved_cwd is not None:
        try:
            os.chdir(saved_cwd)
        except OSError:
            pass
    if saved_path is not None:
        sys.path[:] = saved_path


# ---- env providers: pluggable interpreter-level isolation ------------------

class EnvProvider:
    """Provision an isolated interpreter for a runtime_env kind
    (reference roles: _private/runtime_env/{pip,conda,image_uri}.py —
    each plugin materializes an environment and the worker launches
    inside it). ``prepare`` may block (builds cache-once); it returns
    how to launch a worker for the env. Register concrete providers
    with ``register_env_provider``; tasks/actors whose runtime_env
    carries the kind then run on dedicated workers launched through it
    (core/runtime.py env-keyed pools)."""

    kind: str = ""

    def env_key(self, spec) -> str:
        """Stable content key: equal specs share a worker pool."""
        raise NotImplementedError

    def prepare(self, spec) -> "PreparedEnv":
        """Materialize the env (idempotent; may block on first build)."""
        raise NotImplementedError


class PreparedEnv:
    """How to launch a worker inside an env: the interpreter to exec and
    extra process environment."""

    def __init__(self, python_exe: str,
                 env_vars: Optional[Dict[str, str]] = None):
        self.python_exe = python_exe
        self.env_vars = dict(env_vars or {})


class PipEnvProvider(EnvProvider):
    """The built-in provider: per-requirements-hash virtualenvs."""

    kind = "pip"

    def __init__(self, cache_root: Optional[str] = None):
        self._cache_root = cache_root

    def _root(self) -> str:
        return self._cache_root or os.environ.get(
            "RTPU_PKG_DIR", "/tmp/ray_tpu_pkgs")

    def env_key(self, spec) -> str:
        packages, options = normalize_pip(spec)
        return _pip_env_key(packages, options)

    def prepare(self, spec) -> PreparedEnv:
        packages, options = normalize_pip(spec)
        site = ensure_pip_env(self._root(), packages, options)
        venv_root = os.path.dirname(os.path.dirname(os.path.dirname(site)))
        return PreparedEnv(os.path.join(venv_root, "bin", "python"))


_ENV_PROVIDERS: Dict[str, EnvProvider] = {"pip": PipEnvProvider()}

# runtime_env kinds that NEED a provider; absent one, using them is a
# loud gated error, not a silent no-op (conda/image_uri have nothing
# installable in this image — the interface is how a deployment with a
# conda binary or a container runtime plugs in)
_PROVIDER_KINDS = ("pip", "conda", "image_uri")


def register_env_provider(provider: EnvProvider) -> None:
    """Install (or replace) the provider for ``provider.kind``."""
    if not provider.kind:
        raise ValueError("provider.kind must be a non-empty string")
    _ENV_PROVIDERS[provider.kind] = provider


def resolve_env_provider(runtime_env: Optional[dict]):
    """(kind, provider, spec) for the isolation-bearing part of a
    runtime_env, or None. Raises for a kind with no provider."""
    if not runtime_env:
        return None
    present = [k for k in _PROVIDER_KINDS if runtime_env.get(k)]
    if not present:
        return None
    if len(present) > 1:
        raise ValueError(
            f"runtime_env carries multiple isolation kinds {present}; "
            "pick one of pip/conda/image_uri")
    kind = present[0]
    provider = _ENV_PROVIDERS.get(kind)
    if provider is None:
        raise ValueError(
            f"runtime_env[{kind!r}] requires a registered EnvProvider "
            "(ray_tpu.core.runtime_env.register_env_provider); none is "
            "installed — conda/container isolation needs the host to "
            "supply the environment runtime")
    return kind, provider, runtime_env[kind]
