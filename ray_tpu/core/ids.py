"""Unique identifiers for jobs, tasks, actors, objects, nodes, and placement groups.

TPU-native analogue of the reference's ID types (ref: src/ray/common/id.h,
src/ray/common/id_def.h). IDs are fixed-length random byte strings with a cheap
hex representation. Unlike the reference we do not embed lineage information in
object IDs; ownership metadata lives in the driver-side object directory.
"""

from __future__ import annotations

import os
import threading

from ray_tpu.util.debug_lock import make_lock

_ID_LENGTH = 16  # bytes; reference uses 28 for ObjectID, 16 is plenty single-cluster.


class BaseID:
    """A fixed-length immutable binary identifier."""

    __slots__ = ("_bytes", "_hash")

    LENGTH = _ID_LENGTH

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes) or len(id_bytes) != self.LENGTH:
            raise ValueError(
                f"{type(self).__name__} requires {self.LENGTH} bytes, "
                f"got {id_bytes!r}"
            )
        self._bytes = id_bytes
        self._hash = hash((type(self).__name__, id_bytes))

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.LENGTH))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.LENGTH)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.LENGTH

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __ne__(self, other):
        return not self.__eq__(other)

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    LENGTH = 4


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class ObjectID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class _Counter:
    """Thread-safe monotonically increasing counter (for deterministic sub-IDs)."""

    def __init__(self):
        self._value = 0
        self._lock = make_lock("_IdGen._lock")

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value


_task_counter = _Counter()


def make_task_id(job_id: JobID) -> TaskID:
    """Derive a unique task ID: 4 job bytes + 8 counter bytes + 4 random."""
    n = _task_counter.next()
    return TaskID(job_id.binary() + n.to_bytes(8, "little") + os.urandom(4))


def make_object_id() -> ObjectID:
    return ObjectID.from_random()
