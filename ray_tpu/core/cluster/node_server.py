"""Per-node server: local scheduler + object services behind a TCP RPC.

The capability analogue of the reference's raylet (src/ray/raylet/
node_manager.h:119) + object manager (src/ray/object_manager/
object_manager.h:117): each node embeds the single-node ``Runtime`` (worker
pool, shm store, resource-aware scheduler, local PGs) and this server adds

- payload-level task/actor submission from remote drivers,
- node-to-node object transfer (peer ``fetch``, pull-based, GCS object
  directory as the rendezvous),
- lease-style spillback: a task whose resource request can never be met
  locally is forwarded to a peer whose totals fit (reference:
  cluster_task_manager.cc spillback),
- registration + heartbeats to the GCS, and cluster-wide KV / named actors
  via the GCS.

Run as ``python -m ray_tpu.core.cluster.node_server --gcs HOST:PORT``.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import external_storage, netem, protocol, serialization
from ray_tpu.core.cluster.pull_manager import (PRIO_GET, PRIO_TASK_ARGS,
                                               PRIO_WAIT)
from ray_tpu.core.cluster.ha import HaGcsClient, resync_node
from ray_tpu.core.cluster.rpc import (ClientCache, RpcError, RpcServer,
                                      cluster_authkey)
from ray_tpu.core.config import config
from ray_tpu.core.ids import ActorID, ObjectID, PlacementGroupID, make_task_id
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.runtime import Runtime, _TaskSpec
from ray_tpu.util.debug_lock import make_condition, make_lock
from ray_tpu.exceptions import (ActorDiedError, ActorError, ObjectLostError,
                                ObjectStoreFullError, ObjectTimeoutError,
                                StaleGcsEpochError)

# Tag prefix for ops; kept as plain strings (framed pickle transport).

#: sentinel returned by _fetch_ranged when the payload was written
#: directly into the shm store (zero-copy bulk path) — there is nothing
#: left for the caller to store.
_STORED = object()


class _PullAdmissionTimeout(Exception):
    """Bulk-pull budget stayed full past the wait: retry, don't treat
    the source location as dead."""


def materialize(runtime: Runtime, payload) -> Tuple[str, bytes]:
    """Convert a local payload descriptor into wire-safe ("inline", bytes)."""
    kind, data = payload
    if kind == "inline":
        return payload
    if kind == "spilled":
        path = data[0] if isinstance(data, tuple) else data
        return ("inline", bytes(external_storage.read_buffer(path)))
    oid = ObjectID(data)
    view = runtime.store.get(oid, timeout_ms=0)
    try:
        return ("inline", bytes(view))
    finally:
        del view
        runtime.store.release(oid)


def payload_nbytes(runtime: Runtime, payload) -> Optional[int]:
    """Byte size of a stored payload, or None when it cannot be measured
    cheaply. Sizes feed the GCS object directory for locality-aware
    scheduling; 'unknown' merely opts the object out of locality scoring."""
    kind, data = payload
    if kind == "inline":
        try:
            return len(data)
        except TypeError:
            return None
    if kind == "spilled":
        path = data[0] if isinstance(data, tuple) else data
        try:
            return external_storage.size(path)
        except OSError:
            return None
    oid = ObjectID(data)
    try:
        view = runtime.store.get(oid, timeout_ms=0)
    except (ObjectTimeoutError, ValueError, OSError):
        return None
    try:
        return view.nbytes
    finally:
        del view
        runtime.store.release(oid)


def store_incoming(runtime: Runtime, oid: ObjectID, data: bytes):
    """Store wire bytes locally: shm when large, inline entry otherwise."""
    if oid.binary() in runtime._freed:
        return  # eagerly freed while this transfer was in flight
    if len(data) > serialization.inline_threshold() and not runtime.store.contains(oid):
        try:
            # retain: _store_payload adopts the ref as the tracking pin
            runtime.store.put(oid, data, retain=True)
            runtime._store_payload(oid, ("shm", oid.binary()))
            return
        except (ObjectStoreFullError, ValueError, OSError):
            pass  # store full/closed: keep the object inline instead
    runtime._store_payload(oid, ("inline", data))


class NodeRuntime(Runtime):
    """Runtime with cluster hooks: remote-object fetch, actor-call routing,
    cluster KV, spillback, and location publication."""

    def __init__(self, server: "NodeServer", **kw):
        self._server_ref = server
        super().__init__(**kw)

    def register_package(self, pkg_hash: str, data: bytes) -> None:
        """Nested submissions from this node's workers: publish to the
        GCS KV so spillback peers (and later tasks on any node) can pull
        the package — the local table alone would strand spilled tasks."""
        super().register_package(pkg_hash, data)
        srv = self._server_ref
        if srv is not None:
            key = f"pkg:{pkg_hash}"
            if not srv.gcs.call(("kv", "exists", key, None)):
                srv.gcs.call(("kv", "put", key, data))

    def _get_package(self, pkg_hash: str):
        """Runtime_env package lookup: local table first, then the GCS
        KV blob the submitting driver registered; cache locally."""
        data = super()._get_package(pkg_hash)
        if data is None:
            srv = self._server_ref
            if srv is not None:
                # no RAM cache: workers extract once into the shared
                # on-disk session cache and never re-fetch this hash
                data = srv.gcs.call(("kv", "get", f"pkg:{pkg_hash}", None))
        return data

    # locations: publish every stored object id (with its payload size,
    # for the locality scorer) to the GCS directory
    def _store_payload(self, oid, payload):
        super()._store_payload(oid, payload)
        srv = self._server_ref
        if srv is not None and oid.binary() not in srv._unpublished:
            srv.note_location(oid.binary(), payload_nbytes(self, payload))

    # Worker-originated requests that need cluster awareness: remote-object
    # gets/waits, cluster KV, and calls on actors living on peer nodes.
    def _handle_data_request(self, w, msg):
        srv = self._server_ref
        tag = msg[0]
        if srv is not None:
            if tag in (protocol.REQ_GET, protocol.REQ_WAIT):
                prio = (PRIO_GET if tag == protocol.REQ_GET
                        else PRIO_WAIT)
                for b in msg[1]:
                    srv.ensure_available(b, priority=prio)
            elif tag == protocol.REQ_KV:
                _, op, key, value = msg
                return ("ok", srv.gcs.call(("kv", op, key, value)))
            elif tag == protocol.REQ_FREE:
                # worker-originated free: the object may live on any node
                return ("ok", len(srv.free_cluster_wide(msg[1])))
            elif tag == protocol.REQ_KILL_ACTOR:
                aid = ActorID(msg[1])
                if msg[2]:
                    srv.gcs.try_call(("drop_actor_spec", msg[1]))
                if aid in self._actors:
                    self.kill_actor(aid, no_restart=msg[2])
                    return ("ok",)
                # actor lives elsewhere: route via the GCS actor table
                # (brief retry — creation registration may be racing)
                import sys as _sys

                for _ in range(5):
                    info = (srv.gcs.try_call(("list_actors",), default={})
                            or {})
                    entry = info.get(msg[1])
                    if entry and "node" in entry:
                        try:
                            srv._peers.get(tuple(entry["node"])).call(
                                ("kill_actor", msg[1], msg[2]))
                            return ("ok",)
                        except RpcError:
                            pass
                    time.sleep(0.1)
                print(f"kill_actor: could not route kill for {aid} "
                      f"(no table entry / peer unreachable) — the actor "
                      f"may leak", file=_sys.stderr)
                return ("ok",)
            elif tag == protocol.REQ_ACTOR_CALL:
                _, actor_id_b, method, args_payload, extra, n_returns = msg
                if ActorID(actor_id_b) not in self._actors:
                    refs = srv.forward_actor_call_payload(
                        ActorID(actor_id_b), method, args_payload,
                        extra.get("__deps", []), n_returns,
                        opts=extra.get("__opts"))
                    return ("ok", [r.binary() for r in refs])
            elif tag == protocol.REQ_STREAM_NEXT:
                # generator consumed by a worker on a node that does not
                # own the stream: forward one wait slice to the owner
                _, seed, index, timeout_ms, owner = msg
                if seed not in self._streams and owner is not None:
                    return srv._peers.get(tuple(owner)).call(
                        ("stream_next", seed, index, timeout_ms))
            elif tag == protocol.REQ_STREAM_CONSUMED_ASYNC:
                _, seed, index, owner = msg
                if seed not in self._streams and owner is not None:
                    try:
                        # rtpu-lint: disable=L9 — forwarded credit: a
                        # MONOTONIC watermark (owner takes max), so a
                        # lost/duplicate advance only stalls the
                        # producer one poll slice, never corrupts
                        srv._peers.get(tuple(owner)).call(
                            ("stream_consumed", seed, index))
                    except RpcError:
                        pass  # credit update is best-effort
                    return protocol.NO_REPLY
            elif tag == protocol.REQ_ACTOR_CALL_ASYNC:
                _, actor_id_b, method, args_payload, extra, rids_b = msg
                if ActorID(actor_id_b) not in self._actors:
                    try:
                        srv.forward_actor_call_payload(
                            ActorID(actor_id_b), method, args_payload,
                            extra.get("__deps", []), len(rids_b),
                            return_ids=[ObjectID(b) for b in rids_b],
                            opts=extra.get("__opts"))
                    except BaseException as e:  # noqa: BLE001 — at get()
                        # keep ActorError subtypes intact: a worker-side
                        # get must see ActorUnavailableError as itself,
                        # not masked as a terminal death
                        self._store_error(
                            [ObjectID(b) for b in rids_b],
                            e if isinstance(e, ActorError)
                            else ActorDiedError(
                                f"actor call failed: {e!r}"))
                    return protocol.NO_REPLY
        return super()._handle_data_request(w, msg)

    # spillback: infeasible plain tasks leave for a fitting peer
    def _enqueue(self, spec: _TaskSpec):
        srv = self._server_ref
        if srv is not None:
            if (spec.actor_id is None and spec.request is not None
                    and spec.pg_wire is None and spec.stream is None
                    and not spec.request.is_subset_of(self._total)
                    and srv.spill_task(spec)):
                # stream specs never spill: the stream state (and the
                # consumer's cached owner address) is pinned to this node
                return
            srv.mark_local_products(spec.return_ids)
        super()._enqueue(spec)

    def placement_group_ready_ref(self, pg_id):
        ref = super().placement_group_ready_ref(pg_id)
        if self._server_ref is not None:
            self._server_ref.mark_local_products([ref.id])
        return ref

    # cluster-wide KV lives in the GCS
    def kv_op(self, op: str, key: str, value=None):
        return self._server_ref.gcs.call(("kv", op, key, value))

    # cluster-wide pubsub channels live in the GCS too: a worker's
    # REQ_PUBSUB reaches every driver subscribed anywhere in the cluster
    def pubsub_op(self, op: str, channel: str, arg=None,
                  timeout: float = 0.0):
        gcs = self._server_ref.gcs
        if op == "publish":
            return gcs.call(("publish", channel, arg))
        if op == "poll":
            return gcs.call(("poll", channel, int(arg or 0), timeout))
        raise ValueError(op)

    # named actors are registered cluster-wide
    def _create_actor_from_payload(self, cls_fn_id, args_payload, deps, opts,
                                   actor_id=None):
        name = (opts or {}).get("name")
        srv = self._server_ref
        actor_id = super()._create_actor_from_payload(
            cls_fn_id, args_payload, deps, opts, actor_id=actor_id)
        if srv is not None:
            if name:
                srv.gcs.call(("name_actor", name, actor_id.binary(),
                              srv.address))
            srv.gcs.try_call(("register_actor", actor_id.binary(), {
                "node": srv.address, "name": name, "state": "ALIVE",
                "opts": {k: v for k, v in (opts or {}).items()
                         if k in ("max_restarts", "num_tpus", "num_cpus")},
            }))
        return actor_id

    def _mark_actor_dead(self, state, cause):
        super()._mark_actor_dead(state, cause)
        srv = self._server_ref
        if srv is not None and state.restarts_left == 0:
            name = state.opts.get("name")
            if name:
                srv.gcs.try_call(("drop_actor_name", name,
                                  state.actor_id.binary()))
            srv.gcs.try_call(("register_actor", state.actor_id.binary(),
                              {"state": "DEAD"}))

    # actor calls targeting a peer node's actor (worker-held handles)
    def submit_actor_task(self, actor_id, method, args, kwargs,
                          num_returns=1, options=None):
        if actor_id in self._actors or self._server_ref is None:
            return super().submit_actor_task(
                actor_id, method, args, kwargs, num_returns,
                options=options)
        return self._server_ref.remote_actor_call(
            actor_id, method, args, kwargs, num_returns, options=options)

    def get_actor_method_opts(self, actor_id):
        if actor_id in self._actors or self._server_ref is None:
            return super().get_actor_method_opts(actor_id)
        return self._server_ref.remote_actor_opts(actor_id)

    def kill_actor(self, actor_id, no_restart=True):
        if actor_id in self._actors or self._server_ref is None:
            return super().kill_actor(actor_id, no_restart)
        return self._server_ref.remote_kill_actor(actor_id, no_restart)

    def get_named_actor(self, name: str):
        with self._lock:
            aid = self._named_actors.get(name)
        if aid is not None:
            return aid
        entry = self._server_ref.gcs.call(("get_named_actor", name))
        if entry is None:
            raise ValueError(f"no actor named {name!r}")
        actor_id = ActorID(entry[0])
        self._server_ref.note_remote_actor(actor_id, tuple(entry[1]))
        return actor_id


class NodeServer:
    """One per node process. Owns the NodeRuntime and all cluster links."""

    def __init__(self, gcs_address: Tuple[str, int], num_workers=None,
                 object_store_memory=None, resources: Optional[dict] = None,
                 port: int = 0, authkey: Optional[bytes] = None,
                 labels: Optional[dict] = None):
        self._authkey = authkey or cluster_authkey()
        # ride-through GCS client: calls buffer across a head restart;
        # an epoch change (the head came back as a new process) triggers
        # a full state resync — see _on_gcs_reconnect
        self.gcs = HaGcsClient(tuple(gcs_address), self._authkey,
                               on_reconnect=self._on_gcs_reconnect)
        self.gcs.call(("ping",))
        self._peers = ClientCache(self._authkey)
        self._stop = False
        self._labels = dict(labels or {})
        # GCS incarnation this node's state is known to be synced into;
        # a heartbeat reply carrying a different epoch (or a rejection)
        # re-runs resync_node until it succeeds. _resync_lock serializes
        # concurrent triggers (heartbeat loop + reconnect hook).
        self._synced_epoch: Optional[str] = None
        self._resync_lock = make_lock("NodeServer._resync_lock")
        # True when this server IS the process (python -m ...node_server):
        # a shutdown_node drain then exits the process so the
        # autoscaler's cloud view sees the node release promptly
        self._owns_process = False

        # node workers log to the session files (served via the get_log
        # op); no local monitor thread — the driver pulls, it isn't pushed
        self.runtime = NodeRuntime(
            self, num_workers=num_workers,
            object_store_memory=object_store_memory, log_to_driver=False)
        self.node_id = self.runtime.node_id
        if resources:
            # extend the node's resource pool with custom resources
            from ray_tpu.core.resources import ResourceSet
            extra = ResourceSet(resources)
            self.runtime._total = self.runtime._total + extra
            self.runtime._avail = self.runtime._avail + extra

        self._server = RpcServer(self._handle, self._authkey, port=port)
        self.address = self._server.address
        netem.set_identity("node", self.address)

        # split-brain fencing: newest GCS epoch_seq observed in
        # heartbeat replies. GCS-originated writes (actor restarts,
        # reaps) carry their sender's seq; a token older than this is a
        # partitioned stale head and is rejected with
        # StaleGcsEpochError (see _check_gcs_epoch). Single-writer
        # (heartbeat thread), lock-free monotonic reads elsewhere.
        self._gcs_epoch_seq = 0
        # freed-channel cursor: heartbeat replies piggyback the channel
        # head, so frees that happened while this node was partitioned
        # are replayed (copies reclaimed, tombstones applied) within
        # one heartbeat of heal — and again during resync, BEFORE
        # locations are re-published (the gcs.py stale-copy hole)
        self._freed_seq = 0
        self._freed_cursor_lock = make_lock("NodeServer._freed_cursor_lock")

        # sender-side transfer flow control (reference: push_manager.h —
        # cap outbound chunk bytes in flight; requesters queue FIFO-ish
        # on the condition instead of over-committing sender memory)
        self._push_cv = make_condition("NodeServer._push_cv")
        self._push_inflight = 0
        self._push_waits = 0  # observability: times a chunk had to queue

        # object-location publication (batched); entries are
        # (oid_bytes, nbytes_or_None) — sizes ride along so the GCS
        # directory can feed the driver's locality scorer
        self._loc_lock = make_lock("NodeServer._loc_lock")
        self._loc_pending: List[Tuple[bytes, Optional[int]]] = []
        self._loc_thread = threading.Thread(
            target=self._loc_flush_loop, daemon=True, name="node-locs")
        self._loc_thread.start()

        # owner-death reclamation (see _owner_of above)
        self._owner_thread = threading.Thread(
            target=self._owner_watch_loop, daemon=True, name="node-owners")
        self._owner_thread.start()

        # exactly-once apply for retried submissions: the wire layer (and
        # cluster_core's failover loops) may re-send a submit/actor_call/
        # create_actor whose REPLY was lost. The sender attaches a fresh
        # NONCE per logical request and reuses it on retries; deliberate
        # re-executions (lineage reconstruction, actor restart) mint a new
        # nonce, so they are never confused with duplicate delivery
        # (reference: task-id dedup in
        # src/ray/core_worker/transport/direct_actor_transport.cc)
        self._applied: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._applied_lock = make_lock("NodeServer._applied_lock")

        # ownership: driver-submitted work tags its return objects (and
        # actors) with the owner driver id; when the GCS declares that
        # driver dead, this node reclaims its objects and kills its
        # non-detached actors (reference: owner-failure cleanup,
        # core_worker/reference_count.h:61 + gcs_job_manager.h, done
        # GCS-mediated instead of per-worker RPC). Worker-created objects
        # carry no owner: the node owns them, so detached-actor state
        # survives driver churn. Bounded: oldest entries age out (an aged
        # object merely falls back to normal LRU/spill lifecycle).
        self._owner_of: "OrderedDict[bytes, bytes]" = OrderedDict()
        self._actor_owner: Dict[bytes, bytes] = {}
        self._owner_lock = make_lock("NodeServer._owner_lock")
        self._driver_death_seq = 0

        # in-flight fetch/proxy threads, keyed by oid bytes; _fetch_prio
        # holds each fetch's mutable priority box (upgradable while the
        # pull is queued for admission)
        self._fetching: set = set()
        self._fetch_prio: Dict[bytes, list] = {}
        self._fetch_lock = make_lock("NodeServer._fetch_lock")
        # cross-node pull throughput (cumulative; surfaced via ("state",))
        self._fetch_stats_lock = make_lock("NodeServer._fetch_stats_lock")
        self._fetch_bytes = 0
        self._fetch_seconds = 0.0
        self._fetch_count = 0
        # per-peer suspicion for fetch-candidate ordering: addr ->
        # [latency EWMA s, consecutive transport failures, last-fail
        # monotonic]. A peer that heartbeats the GCS fine but cannot
        # serve data (asymmetric partition) accumulates failures and
        # sinks to the back of every candidate list instead of eating
        # the pull budget first; surfaced via ("state",).
        self._peer_health: Dict[Tuple[str, int], list] = {}
        self._peer_health_lock = make_lock("NodeServer._peer_health_lock")
        # pull admission: bulk transfers reserve their byte size against
        # a store-derived budget, in priority order task-args > get >
        # wait (reference: pull_manager.h:52). Small payloads (below the
        # ranged-transfer threshold) skip admission — they are bounded
        # by the threshold itself.
        from ray_tpu.core.cluster.pull_manager import PullManager
        self.pulls = PullManager(int(
            self.runtime.store.stats()["heap_size"]
            * config.pull_admission_fraction))
        # return ids a local submission will produce (no fetch needed)
        self._local_products: set = set()
        # ids whose stored payload must NOT be published as a location
        # (locally-synthesized error values)
        self._unpublished: set = set()
        # ids latched with a local fetch-timeout error: a later get clears
        # the entry and retries the fetch (the producer may just be slow)
        self._lost_marked: set = set()

        # tasks spilled to peers: first-return-id -> peer address
        self._forwarded: Dict[bytes, Tuple[str, int]] = {}
        # known remote actors: actor_id -> node address
        self._remote_actors: Dict[ActorID, Tuple[str, int]] = {}

        # drain wind-down: latched once when a heartbeat reply says the
        # GCS moved this node to DRAINING (guarded by _drain_lock)
        self._drain_started = False
        self._drain_lock = make_lock("NodeServer._drain_lock")

        self.gcs.call(self.register_msg())
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True, name="node-heartbeat")
        self._hb_thread.start()

    # --------------------------------------------------------------- plumbing

    def register_msg(self) -> tuple:
        """The register_node RPC for THIS node — one builder so initial
        registration, heartbeat-rejection recovery, and post-failover
        resync all register identically (same node_id: the GCS replaces
        the row wholesale, so re-registration never double-counts
        resources)."""
        topo = self.runtime.topology
        return ("register_node", self.node_id.binary(), self.address,
                self.runtime._total.to_dict(),
                {"chips": getattr(topo, "num_chips", 0),
                 "kind": getattr(topo, "kind", "none"),
                 "store": self.runtime.store.name,
                 "hostname": socket.gethostname(), "pid": os.getpid()},
                dict(self._labels))

    def _heartbeat_loop(self):
        interval = config.gcs_heartbeat_interval_s
        while not self._stop:
            rt = self.runtime
            with rt._lock:
                avail = rt._avail.to_dict()
                load = len(rt._task_queue)
                failures = getattr(rt, "_worker_death_count", 0)
            # condensed per-peer suspicion: only peers with a RECENT
            # failure streak ride the heartbeat, so healed edges decay
            # out of the GCS health score instead of pinning it forever
            now = time.monotonic()
            recent = config.gcs_heartbeat_timeout_s
            with self._peer_health_lock:
                peer = {f"{h}:{p}": int(st[1])
                        for (h, p), st in self._peer_health.items()
                        if st[1] > 0 and now - st[2] < recent}
            reply = self.gcs.try_call(
                ("heartbeat", self.node_id.binary(), avail, load,
                 self._gcs_epoch_seq,
                 {"task_failures": failures, "peer_health": peer}))
            if reply is not None:
                seq = reply.get("epoch_seq")
                if isinstance(seq, int) and seq > self._gcs_epoch_seq:
                    self._gcs_epoch_seq = seq
                head = reply.get("freed_head")
                if isinstance(head, int):
                    self._drain_freed(head)
                epoch = reply.get("epoch")
                rejected = not reply.get("accepted", True)
                if self._synced_epoch is None and not rejected:
                    # first contact after our own registration: baseline
                    self._synced_epoch = epoch
                elif rejected or (epoch is not None
                                  and epoch != self._synced_epoch):
                    # marked dead (long GC pause or a healed partition),
                    # or the head restarted (possibly from EMPTY state —
                    # epoch changed even though the rehydrated row
                    # accepted us): re-register and re-publish
                    # locations/actors/PG state. A rejection forces the
                    # resync even under an unchanged epoch: the head
                    # never restarted, it declared US dead, so the
                    # same-epoch dedup must not swallow the re-register.
                    self._resync(epoch, force=rejected)
                if reply.get("state") == "DRAINING":
                    self._begin_drain()
            time.sleep(interval)

    def _begin_drain(self):
        """Heartbeat said the GCS is draining this node: wind down —
        wait for the local queue and in-flight work to empty (actors
        were migrated by the GCS restart FSM; the scheduler cordon
        stops new arrivals), then report node_drained. The process
        stays up serving fetches so consumers can pull results; the
        actual removal is a later (clean) unregister."""
        with self._drain_lock:
            if self._drain_started:
                return
            self._drain_started = True

        def monitor():
            rt = self.runtime
            idle_beats = 0
            while not self._stop and idle_beats < 3:
                with rt._lock:
                    busy = (len(rt._task_queue)
                            + sum(len(w.inflight)
                                  for w in rt._workers.values()))
                idle_beats = idle_beats + 1 if busy == 0 else 0
                time.sleep(0.05)
            if not self._stop:
                # rtpu-lint: disable=L9 — state-machine edge: the GCS
                # applies node_drained only while the node is DRAINING,
                # and a lost reply is healed by the drain-deadline
                # backstop in the GCS monitor (forces DRAINED at grace)
                self.gcs.try_call(("node_drained", self.node_id.binary()))

        threading.Thread(target=monitor, daemon=True,
                         name="node-drain-monitor").start()

    def _clamp_freed_cursor(self, head: int):
        """Rewind the freed-channel cursor after a head restart from
        EMPTY state (the channel seq reset below our watermark)."""
        with self._freed_cursor_lock:
            self._freed_seq = min(self._freed_seq, int(head))

    def _drain_freed(self, head: Optional[int] = None):
        """Apply freed-id broadcasts this node may have missed: a
        driver's free fan-out cannot reach a partitioned node, so on
        heal (heartbeat piggybacks the channel head) or resync we
        replay the ``freed`` channel from our cursor — reclaiming local
        copies and tombstoning the ids so a healed node never serves,
        re-publishes, or re-fetches a stale copy of a freed object.
        ``head`` short-circuits the poll when nothing new was freed; a
        trimmed channel (gap past _CHANNEL_CAP) degrades to the lazy
        per-fetch freed_check, which stays authoritative."""
        with self._freed_cursor_lock:
            since = self._freed_seq
            if head is not None and head <= since:
                return
            msgs = self.gcs.try_call(("poll", "freed", since, 0.0))
            if not msgs:
                return
            freed: List[bytes] = []
            for seq, oid_list in msgs:
                if seq > self._freed_seq:
                    self._freed_seq = seq
                freed.extend(oid_list)
        if not freed:
            return
        # free BEFORE tombstoning: free_objects skips already-tombstoned
        # ids (same ordering free_cluster_wide relies on)
        from ray_tpu.core.runtime import note_freed
        self._op_free(freed)
        rt = self.runtime
        with rt._lock:
            note_freed(rt._freed, freed)

    def _resync(self, epoch: Optional[str], force: bool = False):
        with self._resync_lock:
            if not force and epoch is not None \
                    and self._synced_epoch == epoch:
                return  # a concurrent trigger already resynced into it
            if resync_node(self):
                self._synced_epoch = epoch

    def _on_gcs_reconnect(self, info: dict):
        # runs from whichever thread's call detected the restart — hand
        # the (RPC-heavy) resync to its own thread so that caller's op
        # returns promptly
        threading.Thread(target=self._resync, args=(info.get("epoch"),),
                         daemon=True, name="node-gcs-resync").start()

    def note_location(self, oid_bytes: bytes, nbytes: Optional[int] = None):
        with self._loc_lock:
            self._loc_pending.append((oid_bytes, nbytes))

    def _loc_flush_loop(self):
        while not self._stop:
            time.sleep(0.02)
            with self._loc_lock:
                batch, self._loc_pending = self._loc_pending, []
            if batch:
                ok = self.gcs.try_call(
                    ("loc_add_batch", [b for b, _ in batch],
                     self.address, [n for _, n in batch]))
                if ok is None:
                    # head unreachable (e.g. mid-failover): requeue so
                    # the publications land once it is back, bounded so
                    # a long outage can't grow the buffer without limit
                    with self._loc_lock:
                        self._loc_pending[:0] = batch
                        del self._loc_pending[100_000:]

    def note_remote_actor(self, actor_id: ActorID, addr: Tuple[str, int]):
        self._remote_actors[actor_id] = tuple(addr)

    def _alive_peers(self) -> List[dict]:
        view = self.gcs.call(("list_nodes", True))
        return [n for n in view["nodes"]
                if tuple(n["address"]) != self.address]

    # ---------------------------------------------------- object availability

    def mark_local_products(self, oids):
        for oid in oids:
            self._local_products.add(
                oid if isinstance(oid, bytes) else oid.binary())

    def ensure_available(self, oid_bytes: bytes,
                         hint: Optional[Tuple[str, int]] = None,
                         priority: int = PRIO_GET):
        """Ensure an object id will eventually resolve locally, starting at
        most one background fetch/proxy per id. No-ops for ids a local
        submission will produce, and for already-resolved entries.
        ``priority`` orders bulk-transfer admission (pull_manager.py:
        PRIO_TASK_ARGS=0 > PRIO_GET=1 > PRIO_WAIT=2)."""
        if oid_bytes in self._local_products:
            return
        rt = self.runtime
        oid = ObjectID(oid_bytes)
        if oid_bytes in self._lost_marked:
            # previously latched a fetch-timeout error: clear the entry so
            # this get retries the fetch (waiters of the old error already
            # observed it)
            self._lost_marked.discard(oid_bytes)
            with rt._lock:
                rt._objects.pop(oid, None)
        with rt._lock:
            e = rt._objects.get(oid)
            if e is not None and e.event.is_set():
                return
        with self._fetch_lock:
            if oid_bytes in self._fetching:
                # already pulling: UPGRADE its class if ours is more
                # urgent (reference: PullManager re-prioritizes when a
                # higher-priority requester arrives for the same object)
                box = self._fetch_prio.get(oid_bytes)
                if box is not None and priority < box[0]:
                    box[0] = priority
                return
            self._fetching.add(oid_bytes)
            box = [priority]
            self._fetch_prio[oid_bytes] = box
        fwd = self._forwarded.get(oid_bytes)
        t = threading.Thread(target=self._fetch_object,
                             args=(oid_bytes, fwd or hint, box),
                             daemon=True, name="node-fetch")
        t.start()

    def _fetch_from(self, addr, oid_bytes: bytes,
                    prio_box=None) -> Optional[bytes]:
        """Pull one object from a peer. Large payloads transfer as ranged
        chunks over ``fetch_parallelism`` dedicated connections — the DCN
        bulk path (reference: object_manager chunked pushes over multiple
        gRPC streams); small ones take the single-call fast path."""
        from ray_tpu.core.config import config as cfg

        threshold = cfg.fetch_parallel_threshold_bytes
        t0 = time.monotonic()
        data = self._peers.get(addr).call(
            ("fetch", oid_bytes, threshold if threshold > 0 else None))
        if data is None:
            return None
        if data[0] != "size":
            self._note_fetch(len(data[1]), time.monotonic() - t0)
            return data[1]
        size = data[1]

        # bulk transfer: reserve the payload size against the pull
        # budget, in priority order (reference: pull_manager.h:52). A
        # timed-out reservation surfaces as a retriable failure — the
        # caller's fetch loop re-attempts, so pressure delays, never
        # deadlocks. The priority BOX rides into acquire: a concurrent
        # upgrade (ensure_available from a task-args requester) re-ranks
        # the waiter in place without losing its queue position.
        prio_box = prio_box if prio_box is not None else [PRIO_GET]
        requested_ts = time.time()
        if not self.pulls.acquire(size, prio_box,
                                  timeout=cfg.pull_acquire_timeout_s):
            raise _PullAdmissionTimeout(
                f"pull admission timed out for {size}B from "
                f"{addr[0]}:{addr[1]} after "
                f"{cfg.pull_acquire_timeout_s:g}s (priority {prio_box[0]}; "
                f"flag pull_acquire_timeout_s)")
        priority = prio_box[0]  # class at grant time, for the timeline
        granted_ts = time.time()
        granted_mono = time.monotonic()
        ok = False
        try:
            data = self._fetch_ranged(addr, oid_bytes, size, cfg)
            ok = True
            self._note_fetch(size, time.monotonic() - granted_mono)
            return data
        finally:
            self.pulls.release(size)
            rt = self.runtime
            if rt._events is not None and len(rt._events) < 200_000:
                from ray_tpu.core.cluster.pull_manager import prio_name
                rt._events.append({
                    "task_id": oid_bytes.hex(),
                    "parent_task_id": None,
                    "fn": (f"pull:{prio_name(priority)}"
                           + ("" if ok else ":failed")),
                    "actor": None, "worker": "pull", "pid": 0,
                    "submitted": requested_ts,
                    "dispatched": granted_ts,
                    "done": time.time(),
                })

    def _fetch_ranged(self, addr, oid_bytes: bytes, size: int, cfg):
        """Chunked bulk pull. The normal path pre-creates the shm store
        allocation and writes every ranged chunk straight into it, then
        seals — ONE copy from socket to store, where the old
        assemble-into-bytearray-then-bytes() path held two full copies at
        peak. Returns ``_STORED`` when the payload landed in the store
        (caller skips store_incoming), else the assembled bytes (store
        full / id already allocated: rare pressure fallback)."""
        rt = self.runtime
        oid = ObjectID(oid_bytes)
        chunk = max(1 << 20, cfg.fetch_chunk_bytes)
        nstreams = max(1, min(cfg.fetch_parallelism,
                              (size + chunk - 1) // chunk))
        offsets = list(range(0, size, chunk))
        dst = None
        try:
            if oid_bytes not in rt._freed and not rt.store.contains(oid):
                try:
                    dst = rt.store.create_object(oid, size)
                except (ObjectStoreFullError, ValueError, OSError):
                    dst = None  # heap-assembly fallback below
            buf = None if dst is not None else bytearray(size)
            out = dst if dst is not None else memoryview(buf)
            failed: List[str] = []
            idx_lock = make_lock("NodeServer._fetch_ranged.<idx>")
            next_idx = [0]

            client = self._peers.get(addr)  # pooled: N concurrent calls
            # use N connections, kept for future transfers to the same peer

            def puller():
                try:
                    while not failed:
                        with idx_lock:
                            if next_idx[0] >= len(offsets):
                                return
                            off = offsets[next_idx[0]]
                            next_idx[0] += 1
                        n = min(chunk, size - off)
                        part = client.call(
                            ("fetch_range", oid_bytes, off, n))
                        if part is None or len(part) != n:
                            failed.append(f"range {off}+{n} unavailable")
                            return
                        out[off:off + n] = part
                except Exception as e:  # noqa: BLE001
                    failed.append(repr(e))

            threads = [threading.Thread(target=puller, daemon=True,
                                        name="node-fetch-range")
                       for _ in range(nstreams)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        except BaseException:
            # transfer machinery failed before the verdict below (e.g.
            # dialing the peer raised): an unsealed allocation is
            # invisible to getters and reclaimed only at store close —
            # abort it before surfacing
            if dst is not None:
                rt.store.release(oid)
                rt.store.delete(oid)
            raise
        if failed:
            if dst is not None:
                # abort the unsealed allocation: drop the creator ref,
                # then free (an unsealed object is invisible to getters,
                # so nobody else can hold it)
                rt.store.release(oid)
                rt.store.delete(oid)
            raise RpcError(f"chunked fetch of {size} bytes from "
                           f"{addr} failed: {failed[0]}")
        if dst is not None:
            rt.store.seal(oid, retain=True)
            if oid_bytes in rt._freed:
                # freed while the transfer was in flight: reclaim instead
                # of publishing (mirrors store_incoming's tombstone check)
                rt.store.release(oid)
                rt.store.delete(oid)
                return _STORED
            # retain'd ref hands off to the tracking pin; publishes the
            # location (with size) like any other stored payload
            rt._store_payload(oid, ("shm", oid_bytes))
            return _STORED
        return bytes(buf)

    def _note_fetch(self, nbytes: int, seconds: float):
        with self._fetch_stats_lock:
            self._fetch_bytes += nbytes
            self._fetch_seconds += seconds
            self._fetch_count += 1

    def _note_peer(self, addr, ok: bool, elapsed: float = 0.0):
        """Update per-peer suspicion after a transfer attempt: latency
        EWMA plus a consecutive-transport-failure counter. Under an
        asymmetric partition a peer may accept our TCP connect yet never
        deliver (one-way netem/blackhole) — the failure streak, not the
        connect, is what marks it suspect."""
        addr = tuple(addr)
        with self._peer_health_lock:
            h = self._peer_health.setdefault(addr, [0.0, 0, 0.0])
            if ok:
                h[0] = elapsed if h[0] == 0.0 else 0.8 * h[0] + 0.2 * elapsed
                h[1] = 0
            else:
                h[1] += 1
                h[2] = time.monotonic()

    def _peer_suspicion(self, addr) -> Tuple[int, float]:
        """Sort key for fetch candidates: peers with an active failure
        streak are tried LAST, ties broken by latency EWMA — a fetch
        under an asymmetric partition fails over to a reachable copy
        instead of burning its budget on the severed edge."""
        with self._peer_health_lock:
            h = self._peer_health.get(tuple(addr))
            return (0, 0.0) if h is None else (h[1], h[0])

    def _fetch_object(self, oid_bytes: bytes, hint, prio_box=None):
        rt = self.runtime
        oid = ObjectID(oid_bytes)
        prio_box = prio_box if prio_box is not None else [PRIO_GET]
        started = time.monotonic()
        deadline = started + 600.0
        transport_failures = 0
        suspects: Dict[Tuple[str, int], str] = {}
        try:
            while not self._stop:
                e = rt._objects.get(oid)
                if e is not None and e.event.is_set():
                    return  # resolved locally meanwhile
                addrs: List[Tuple[str, int]] = []
                if hint:
                    addrs.append(tuple(hint))
                locs = self.gcs.try_call(("loc_get", oid_bytes, 0.5),
                                         default=[])
                addrs.extend(tuple(a) for a in locs or [])
                # dedup, then try the least-suspect peers first: under an
                # asymmetric partition the severed copy fails in
                # milliseconds and the fetch fails over to a healthy
                # replica instead of re-dialing the dead edge
                addrs = sorted(dict.fromkeys(addrs),
                               key=self._peer_suspicion)
                for addr in addrs:
                    if addr == self.address:
                        continue
                    attempt_t0 = time.monotonic()
                    try:
                        data = self._fetch_from(addr, oid_bytes,
                                                prio_box)
                    except _PullAdmissionTimeout:
                        # location is fine — the budget was busy.
                        # Age the priority (a starved get/wait climbs to
                        # task-args class, whose FIFO bounds its wait)
                        # and push the loss deadline out: congestion is
                        # delay, never data loss.
                        prio_box[0] = max(0, prio_box[0] - 1)
                        deadline = max(deadline,
                                       time.monotonic() + 300.0)
                        continue
                    except (RpcError, Exception) as err:  # noqa: BLE001
                        self._note_peer(addr, False)
                        transport_failures += 1
                        suspects[addr] = f"{type(err).__name__}: {err}"
                        if self._peer_suspicion(addr)[0] >= 3:
                            # a sustained streak, not a blip: retract the
                            # location so other pulls stop dialing it. A
                            # sub-second partition keeps its directory
                            # entry and resumes on heal.
                            self.gcs.try_call(
                                ("loc_drop", oid_bytes, addr))
                        continue
                    if data is _STORED:
                        self._note_peer(
                            addr, True, time.monotonic() - attempt_t0)
                        return  # zero-copy path already sealed + published
                    if data is not None:
                        self._note_peer(
                            addr, True, time.monotonic() - attempt_t0)
                        store_incoming(rt, oid, data)
                        return
                # no copy anywhere: an eagerly-freed object must fail NOW
                # with the documented message, not spin out the deadline
                if self.gcs.try_call(("freed_check", oid_bytes),
                                     default=False):
                    self._unpublished.add(oid_bytes)
                    self._lost_marked.add(oid_bytes)
                    try:
                        rt._store_payload(oid, protocol.serialize_value(
                            protocol.ErrorValue(ObjectLostError(
                                f"object {oid} was freed by ray_tpu.free() "
                                f"and is not reconstructable")), store=None))
                    finally:
                        self._unpublished.discard(oid_bytes)
                    return
                if transport_failures >= 8 and \
                        time.monotonic() - started > 2.0:
                    # every known copy sits behind a severed edge and the
                    # failure streak has outlived the blip grace: latch
                    # the loss NOW (naming the unreachable peers) so the
                    # waiter's reconstruction/retry machinery kicks in
                    # seconds after the partition, not after the full
                    # 600s pull budget. A sub-second partition never gets
                    # here — attempts resume as soon as it heals.
                    who = "; ".join(
                        f"{a[0]}:{a[1]} ({why})"
                        for a, why in sorted(suspects.items()))
                    self._unpublished.add(oid_bytes)
                    self._lost_marked.add(oid_bytes)
                    try:
                        rt._store_payload(oid, protocol.serialize_value(
                            protocol.ErrorValue(ObjectLostError(
                                f"object {oid} unreachable: every known "
                                f"copy is behind a partitioned peer after "
                                f"{transport_failures} transport failures"
                                f" — {who}")), store=None))
                    finally:
                        self._unpublished.discard(oid_bytes)
                    return
                if time.monotonic() > deadline:
                    # Surface ObjectLostError to local waiters (queued
                    # tasks would otherwise hang forever on the dep) but
                    # never publish this node as a location for it — the
                    # error value is local, not the object.
                    oid_b = oid.binary()
                    self._unpublished.add(oid_b)
                    self._lost_marked.add(oid_b)
                    try:
                        rt._store_payload(oid, protocol.serialize_value(
                            protocol.ErrorValue(ObjectLostError(
                                f"object {oid} could not be fetched from "
                                f"any node within 600s")), store=None))
                    finally:
                        self._unpublished.discard(oid_b)
                    return
                time.sleep(0.05)
        finally:
            with self._fetch_lock:
                self._fetching.discard(oid_bytes)
                self._fetch_prio.pop(oid_bytes, None)

    # --------------------------------------------------------------- spilling

    def spill_task(self, spec: _TaskSpec) -> bool:
        """Forward an infeasible task to a peer whose totals fit. Returns
        True when spilled."""
        try:
            peers = self._alive_peers()
        except RpcError:
            return False
        req = spec.request.to_dict()
        fit = [n for n in peers
               if all(n["resources"].get(k, 0) >= v for k, v in req.items())]
        if not fit:
            return False
        fit.sort(key=lambda n: (n["load"],
                                -sum(n["avail"].get(k, 0) for k in req)))
        target = tuple(fit[0]["address"])
        rt = self.runtime
        with rt._lock:
            pickled_fn = rt._functions.get(spec.fn_id)
        payload = materialize(rt, spec.args_payload)
        msg = ("submit", spec.fn_id, pickled_fn, payload,
               [d.binary() for d in spec.deps],
               [d.binary() for d in spec.nested_deps],
               [r.binary() for r in spec.return_ids],
               spec.options, None, os.urandom(16))
        try:
            self._peers.get(target).call(msg)
        except RpcError:
            return False
        for rid in spec.return_ids:
            self._forwarded[rid.binary()] = target
        # free the resources this spec reserved from accounting (it never
        # acquired; request simply never enters the local pool)
        return True

    # ------------------------------------------------- remote actor routing

    def _actor_addr(self, actor_id: ActorID) -> Tuple[str, int]:
        addr = self._remote_actors.get(actor_id)
        if addr is None:
            table = self.gcs.call(("list_actors",))
            info = table.get(actor_id.binary())
            if info is None or info.get("state") == "DEAD" or "node" not in info:
                raise ActorDiedError(f"unknown actor {actor_id}")
            addr = tuple(info["node"])
            self._remote_actors[actor_id] = addr
        return addr

    def remote_actor_call(self, actor_id: ActorID, method: str, args, kwargs,
                          num_returns: int, options=None) -> List[ObjectRef]:
        rt = self.runtime
        args2, kwargs2, deps = rt._swap_top_level_refs(args, kwargs)
        payload, nested = protocol.serialize_args(args2, kwargs2, store=None)
        return self._send_actor_call(
            actor_id, method, payload, [d.binary() for d in deps],
            [r.binary() for r in nested], num_returns, opts=options)

    def forward_actor_call_payload(self, actor_id: ActorID, method: str,
                                   args_payload, deps: List[bytes],
                                   num_returns: int,
                                   return_ids: Optional[List[ObjectID]]
                                   = None, opts=None) -> List[ObjectRef]:
        """Route a worker's call on a peer node's actor (payload level).
        ``return_ids`` preset = fire-and-forget caller already handed
        refs out."""
        return self._send_actor_call(
            actor_id, method, materialize(self.runtime, args_payload),
            list(deps), [], num_returns, return_ids=return_ids, opts=opts)

    def _send_actor_call(self, actor_id, method, payload, deps, nested,
                         num_returns, return_ids=None,
                         opts=None) -> List[ObjectRef]:
        rt = self.runtime
        if return_ids is None:
            return_ids = [ObjectID.from_random()
                          for _ in range(num_returns)]
        msg = ("actor_call", actor_id.binary(), method, payload, deps, nested,
               [r.binary() for r in return_ids], os.urandom(16), None, False,
               dict(opts or {}))
        addr = self._actor_addr(actor_id)
        try:
            self._peers.get(addr).call(msg)
        except (RpcError, ActorDiedError):
            # stale cache: the actor may have been restarted on another
            # node. The GCS re-registers it only once the new incarnation
            # is up, so keep re-resolving for the restart window — a call
            # racing a cross-node restart must land on the new
            # incarnation, not surface a transient routing error.
            # _actor_addr itself raising (table says DEAD/unknown) stays
            # terminal: that's a real death, not a stale route.
            self._remote_actors.pop(actor_id, None)
            deadline = time.monotonic() + config.actor_restart_timeout_s
            while True:
                addr = self._actor_addr(actor_id)
                try:
                    self._peers.get(addr).call(msg)
                    break
                except (RpcError, ActorDiedError):
                    self._remote_actors.pop(actor_id, None)
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.2)
        for rid in return_ids:
            rt._entry(rid)
            self.ensure_available(rid.binary(), hint=addr)
        return [ObjectRef(rid, core=rt) for rid in return_ids]

    def remote_actor_opts(self, actor_id: ActorID) -> dict:
        addr = self._actor_addr(actor_id)
        return self._peers.get(addr).call(("actor_opts", actor_id.binary()))

    def remote_kill_actor(self, actor_id: ActorID, no_restart: bool):
        addr = self._actor_addr(actor_id)
        return self._peers.get(addr).call(
            ("kill_actor", actor_id.binary(), no_restart))

    # ---------------------------------------------------------------- handler

    def _handle(self, msg, ctx) -> Any:
        op = msg[0]
        fn = getattr(self, "_op_" + op, None)
        if fn is None:
            raise ValueError(f"unknown node op {op!r}")
        return fn(*msg[1:])

    def _op_ping(self):
        return "pong"

    def _op_status(self):
        rt = self.runtime
        with rt._lock:
            return {
                "node_id": self.node_id.binary(),
                "address": self.address,
                "total": rt._total.to_dict(),
                "avail": rt._avail.to_dict(),
                "load": len(rt._task_queue),
                "num_workers": len(rt._workers),
                "store": rt.store.stats(),
                "oom_kills": getattr(rt, "_oom_kill_count", 0),
            }

    def _op_state(self):
        s = self.runtime.state_summary()
        s["push_waits"] = self._push_waits  # sender-side backpressure hits
        s["pulls"] = self.pulls.stats()     # admission-control occupancy
        with self._fetch_stats_lock:        # cross-node pull throughput
            s["fetch"] = {"bytes": self._fetch_bytes,
                          "seconds": round(self._fetch_seconds, 6),
                          "count": self._fetch_count}
        s["gcs_epoch_seq"] = self._gcs_epoch_seq  # split-brain fence watermark
        with self._peer_health_lock:        # per-peer suspicion (EWMA, streak)
            s["peer_health"] = {
                f"{a[0]}:{a[1]}": {"ewma_s": round(h[0], 6),
                                   "fail_streak": h[1]}
                for a, h in self._peer_health.items()}
        return s

    def _op_netem(self, cmd, *args):
        """Control plane for the deterministic network-fault shim: the
        cluster fixture arms/heals partitions in THIS process over an
        unaffected edge (see core/netem.py)."""
        return netem.control(cmd, *args)

    def _check_gcs_epoch(self, token):
        """Reject a GCS-originated write stamped by an incarnation older
        than the newest this node has seen (split-brain fence: a
        partitioned-but-alive old head must not restart or reap actors
        here). ``None`` = pre-epoch caller or node-local path: allowed."""
        seen = self._gcs_epoch_seq
        if token is not None and seen and int(token) < seen:
            raise StaleGcsEpochError(
                f"write from stale GCS incarnation rejected by node "
                f"{self.address[0]}:{self.address[1]}",
                stale_seq=int(token), current_seq=seen)

    def _op_stack_dump(self):
        return self.runtime.stack_dump()

    def _op_task_events(self):
        """Flag-gated task timeline events recorded by this node's
        runtime (driver aggregates across nodes for ray_tpu.timeline).
        None = recording disabled on this node."""
        ev = self.runtime._events
        return None if ev is None else list(ev)

    def _op_list_logs(self):
        from ray_tpu.core.log_monitor import list_log_files

        return list_log_files(self.runtime.log_dir)

    def _op_get_log(self, name: str, tail_lines: int = 1000):
        from ray_tpu.core.log_monitor import read_log_file

        return read_log_file(self.runtime.log_dir, name, tail_lines)

    def _op_register_fn(self, fn_id: bytes, pickled: bytes):
        rt = self.runtime
        with rt._lock:
            rt._functions.setdefault(fn_id, pickled)
        return True

    _APPLIED_CAP = 16384
    _OWNED_CAP = 1 << 18

    def _tag_owner(self, oid_bytes_list, owner):
        with self._owner_lock:
            for b in oid_bytes_list:
                self._owner_of[b] = owner
            while len(self._owner_of) > self._OWNED_CAP:
                self._owner_of.popitem(last=False)

    def _untag_owner(self, oid_bytes_list):
        with self._owner_lock:
            for b in oid_bytes_list:
                self._owner_of.pop(b, None)

    def _dedup(self, nonce, fn):
        """Run ``fn`` exactly once per nonce (at-most-once apply).

        A duplicate delivery (lost-reply retry) returns the original's
        result; a duplicate racing an IN-PROGRESS original waits for it
        (wip latch) instead of reporting phantom success. The result is
        published only on completion — if the original raises, the entry
        is dropped so a retry legitimately re-runs. ``nonce=None`` (older
        peers / no retry in play) just runs ``fn``."""
        if nonce is None:
            return fn()
        while True:
            with self._applied_lock:
                ent = self._applied.get(nonce)
                if ent is None:
                    ev = threading.Event()
                    self._applied[nonce] = ("wip", ev)
                    break
            if ent[0] == "done":
                return ent[1]
            ent[1].wait(600)  # original still applying: wait, re-check
        try:
            result = fn()
        except BaseException:
            with self._applied_lock:
                self._applied.pop(nonce, None)
            ev.set()
            raise
        with self._applied_lock:
            self._applied[nonce] = ("done", result)
            # evict oldest DONE entries; wip entries (rare, transient) go
            # back at the tail. O(evictions), not O(cap).
            requeue = []
            while len(self._applied) - len(requeue) > self._APPLIED_CAP:
                k, v = self._applied.popitem(last=False)
                if v[0] == "wip":
                    requeue.append((k, v))
            for k, v in requeue:
                self._applied[k] = v
        ev.set()
        return result

    def _op_submit(self, fn_id, pickled_fn, args_payload, deps, nested,
                   return_ids, options, locations, nonce=None, owner=None):
        return self._dedup(nonce, lambda: self._do_submit(
            fn_id, pickled_fn, args_payload, deps, nested, return_ids,
            options, locations, owner))

    def _do_submit(self, fn_id, pickled_fn, args_payload, deps, nested,
                   return_ids, options, locations, owner=None):
        rt = self.runtime
        if owner is not None:
            self._tag_owner(return_ids, owner)
        if pickled_fn is not None:
            with rt._lock:
                rt._functions.setdefault(fn_id, pickled_fn)
        with rt._lock:
            known = fn_id in rt._functions
        if not known:
            raise KeyError(f"function {fn_id.hex()} not registered on node")
        dep_ids = [ObjectID(b) for b in deps]
        ret_ids = [ObjectID(b) for b in return_ids]
        for b, d in zip(deps, dep_ids):
            self.ensure_available(
                b, hint=tuple(locations[b]) if locations and b in locations
                else None, priority=PRIO_TASK_ARGS)
        for b in nested:
            self.ensure_available(b, priority=PRIO_TASK_ARGS)
        task_id = make_task_id(rt.job_id)
        for rid in ret_ids:
            rt._entry(rid)
        opts = dict(options or {})
        streaming = bool(opts.pop("__stream", False))
        spec = _TaskSpec(task_id, fn_id, args_payload, dep_ids, ret_ids,
                         opts)
        spec.nested_deps = [ObjectID(b) for b in nested]
        spec.request, spec.pg_wire = rt._prepare_request(
            dict(opts), is_actor=False)
        rt._cancellable[ret_ids[0].binary()] = spec
        if streaming:
            # this node owns the stream state: the consumer's stream_next
            # ops route here (ClusterCore caches seed -> this address)
            seed = ret_ids[0].binary()
            spec.stream = rt._stream_opts(seed)
            rt._register_stream(seed)
        rt._enqueue(spec)
        return True

    def _op_get(self, oid_bytes_list, timeout, allow_shm=False):
        rt = self.runtime
        deadline = None if timeout is None else time.monotonic() + timeout
        for b in oid_bytes_list:
            if b in rt._freed:
                raise ObjectLostError(
                    f"object {b.hex()} was freed by ray_tpu.free() and is "
                    f"not reconstructable")
            self.ensure_available(b)
        out = {}
        for b in oid_bytes_list:
            e = rt._entry(ObjectID(b))
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            if not e.event.wait(remaining):
                from ray_tpu.exceptions import GetTimeoutError
                raise GetTimeoutError(f"get timed out for {b.hex()}")
            if allow_shm and e.payload[0] == "shm":
                # same-host driver reads the store zero-copy
                out[b] = e.payload
            else:
                out[b] = materialize(rt, e.payload)
        return out

    def _op_fetch(self, oid_bytes, max_bytes=None):
        """Peer pull: ("inline", payload_bytes), or ("size", n) when the
        payload exceeds ``max_bytes`` (caller switches to ranged pulls),
        or None if this node does not hold the object (no recursive
        fetch)."""
        rt = self.runtime
        oid = ObjectID(oid_bytes)
        with rt._lock:
            e = rt._objects.get(oid)
            # a freed id must not be served: the entry keeps its payload
            # as a tombstone, but the storage is reclaimed ("free means
            # dead" — peers see "not held", then the GCS tombstone)
            if (e is None or not e.event.is_set()
                    or oid_bytes in rt._freed):
                return None
            payload = e.payload
        if max_bytes is not None:
            size = self._op_fetch_size(oid_bytes)
            if size is not None and size >= max_bytes:
                return ("size", size)
        return materialize(rt, payload)

    def _op_fetch_size(self, oid_bytes):
        """Payload byte count for range-based transfer, or None."""
        rt = self.runtime
        oid = ObjectID(oid_bytes)
        with rt._lock:
            e = rt._objects.get(oid)
            if (e is None or not e.event.is_set()
                    or oid_bytes in rt._freed):
                return None
            kind, data = e.payload
        if kind == "inline":
            return len(data)
        if kind == "spilled":
            path = data[0] if isinstance(data, tuple) else data
            return external_storage.size(path)
        view = rt.store.get(oid, timeout_ms=0)
        try:
            return view.nbytes
        finally:
            del view
            rt.store.release(oid)

    def _op_fetch_range(self, oid_bytes, offset: int, length: int):
        """One chunk of a payload (the DCN bulk path: a puller runs many
        of these concurrently on separate connections). Serves shm-backed
        objects without materializing the whole payload, under the
        sender-side in-flight byte cap (push_max_inflight_bytes)."""
        cap = config.push_max_inflight_bytes
        if cap > 0:
            with self._push_cv:
                if self._push_inflight + length > cap \
                        and self._push_inflight > 0:
                    self._push_waits += 1
                while (self._push_inflight + length > cap
                       and self._push_inflight > 0):
                    self._push_cv.wait(timeout=1.0)
                self._push_inflight += length
            try:
                return self._fetch_range_inner(oid_bytes, offset, length)
            finally:
                with self._push_cv:
                    self._push_inflight -= length
                    self._push_cv.notify_all()
        return self._fetch_range_inner(oid_bytes, offset, length)

    def _fetch_range_inner(self, oid_bytes, offset: int, length: int):
        rt = self.runtime
        oid = ObjectID(oid_bytes)
        with rt._lock:
            e = rt._objects.get(oid)
            if (e is None or not e.event.is_set()
                    or oid_bytes in rt._freed):
                return None
            kind, data = e.payload
        if kind == "inline":
            return bytes(data[offset:offset + length])
        if kind == "spilled":
            path = data[0] if isinstance(data, tuple) else data
            try:
                return external_storage.read_range(path, offset, length)
            except Exception:  # noqa: BLE001
                return None
        view = rt.store.get(oid, timeout_ms=0)
        try:
            return bytes(view[offset:offset + length])
        finally:
            del view
            rt.store.release(oid)

    def _owner_watch_loop(self):
        """Poll the GCS for driver deaths; reclaim a dead driver's
        objects and kill its non-detached actors on THIS node. Every node
        runs the same loop over its own ownership maps, so cleanup needs
        no fan-out coordinator (reference: owner-failure cleanup paths of
        reference_count.h:61 / gcs_job_manager.h)."""
        while not self._stop:
            time.sleep(config.gcs_heartbeat_interval_s * 2)
            try:
                deaths = self.gcs.call(
                    ("driver_deaths_since", self._driver_death_seq))
            # rtpu-lint: disable=L4 — crash-proof daemon loop: call()
            # re-raises arbitrary picklable remote exceptions, and a
            # failed poll (GCS down/restarting) just retries next tick
            except Exception:  # noqa: BLE001
                continue
            for seq, driver_id in deaths:
                self._driver_death_seq = max(self._driver_death_seq, seq)
                try:
                    self._reclaim_owner(driver_id)
                # rtpu-lint: disable=L4 — cleanup is best-effort: a
                # partly-reclaimed owner must not wedge the watch loop;
                # unfreed ids are re-reported on the next death record
                except Exception:  # noqa: BLE001
                    pass

    def _reclaim_owner(self, driver_id: bytes):
        with self._owner_lock:
            dead_oids = [b for b, o in self._owner_of.items()
                         if o == driver_id]
            dead_actors = [b for b, o in self._actor_owner.items()
                           if o == driver_id]
            for b in dead_oids:
                self._owner_of.pop(b, None)
            for b in dead_actors:
                self._actor_owner.pop(b, None)
        if dead_oids:
            self._op_free(dead_oids)
        for aid_b in dead_actors:
            try:
                self.runtime.kill_actor(ActorID(aid_b), no_restart=True)
            # rtpu-lint: disable=L4 — the actor may already be dead or
            # mid-restart; reclaim must still process the remaining ones
            except Exception:  # noqa: BLE001
                pass

    def _op_owner_cleanup(self, driver_id: bytes):
        """Test/ops hook: reclaim one owner's footprint immediately."""
        self._reclaim_owner(driver_id)
        return True

    def _op_free(self, oid_bytes_list):
        """Eager deletion (driver free fan-out). Returns the ids actually
        freed here (the driver unions across nodes — a replicated object
        must count once). The freed-error marker is local — never
        republish these ids as locations."""
        for b in oid_bytes_list:
            self._unpublished.add(b)
        try:
            freed = self.runtime.free_objects(oid_bytes_list,
                                              return_ids=True)
        finally:
            for b in oid_bytes_list:
                self._unpublished.discard(b)
        self._untag_owner(oid_bytes_list)
        for b in oid_bytes_list:
            self.gcs.try_call(("loc_drop", b, self.address))
        return freed

    def free_cluster_wide(self, oid_bytes_list) -> set:
        """Worker-originated free: the copy may live on ANY node (a
        worker on node A freeing an object produced on node B), so free
        locally, then fan out to EVERY alive peer — the location
        directory only covers transferred copies, not a producer's
        original, so loc_get alone would miss the primary copy (the
        driver-side free fans out the same way). Returns the union of
        ids freed anywhere."""
        freed = set(self._op_free(oid_bytes_list) or [])
        for info in self._alive_peers():
            addr = tuple(info["address"])
            try:
                # rtpu-lint: disable=L9 — per-peer fan-out, not a
                # re-send; free of an unknown/tombstoned id is a no-op
                # and the freed_add tombstones published below are the
                # authority a missed peer converges on
                freed.update(self._peers.get(addr).call(
                    ("free", list(oid_bytes_list))) or [])
            except RpcError:
                continue
        if freed:
            # publish tombstones (bounded GCS table): fetch loops and the
            # driver's lineage reconstruction consult them, so a
            # worker-freed object dies fast everywhere instead of being
            # spun on or resurrected ("free means dead")
            self.gcs.try_call(("freed_add", list(freed)))
            # close the prefetch race: a transfer of one of these ids that
            # started before the free can land locally AFTER the local
            # _op_free above ran (this very node prefetches nested task
            # deps). Re-free anything that landed meanwhile, THEN
            # tombstone locally so later arrivals are never stored or
            # served (free_objects skips already-tombstoned ids, so the
            # order matters).
            self._op_free(list(freed))
            from ray_tpu.core.runtime import note_freed

            rt = self.runtime
            with rt._lock:
                note_freed(rt._freed, freed)
        return freed

    def _op_has(self, oid_bytes):
        rt = self.runtime
        with rt._lock:
            e = rt._objects.get(ObjectID(oid_bytes))
            return e is not None and e.event.is_set()

    def _op_wait(self, oid_bytes_list, num_returns, timeout):
        rt = self.runtime
        for b in oid_bytes_list:
            self.ensure_available(b, priority=PRIO_WAIT)
        refs = [ObjectRef(ObjectID(b), core=rt) for b in oid_bytes_list]
        ready, rest = rt.wait(refs, num_returns=num_returns, timeout=timeout)
        return [r.binary() for r in ready], [r.binary() for r in rest]

    def _op_put(self, data: bytes, oid_bytes=None, owner=None):
        rt = self.runtime
        oid = ObjectID(oid_bytes) if oid_bytes else ObjectID.from_random()
        store_incoming(rt, oid, data)
        if owner is not None:
            self._tag_owner([oid.binary()], owner)
        return oid.binary()

    def _op_release(self, oid_bytes_list):
        rt = self.runtime
        for b in oid_bytes_list:
            oid = ObjectID(b)
            with rt._lock:
                e = rt._objects.pop(oid, None)
            if (e is not None and e.payload is not None
                    and e.payload[0] == "spilled"):
                try:
                    os.unlink(e.payload[1][0])
                except OSError:
                    pass
            # drop the owner tracking pin so delete can actually reclaim
            with rt._spill_lock:
                had_pin = rt._pinned.pop(b, None) is not None
            try:
                if had_pin:
                    rt.store.release(oid)
                rt.store.delete(oid)
            # rtpu-lint: disable=L4 — the object may have been evicted or
            # the store closed under us; release is best-effort and the
            # location drop below must still be published
            except Exception:  # noqa: BLE001
                pass
            self.gcs.try_call(("loc_drop", b, self.address))
        return True

    def _op_cancel(self, oid_bytes, force):
        rt = self.runtime
        return rt.cancel_task(ObjectRef(ObjectID(oid_bytes), core=rt),
                              force=force)

    # -- streaming returns (stream state lives on the owning node; the
    #    driver and peer nodes poll it with bounded slices)

    def _op_stream_next(self, seed, index, timeout_ms):
        """One bounded wait slice against a local stream. Returns
        ("ref", rid_b) | ("end", count) | ("pending",)."""
        rt = self.runtime
        st = rt._streams.get(seed)
        if st is None:
            raise ValueError(f"unknown stream {seed.hex()}")
        deadline = time.monotonic() + timeout_ms / 1000.0
        with st.cond:
            while True:
                hit = rt._stream_poll_locked(st, index)
                if hit is not None:
                    return hit
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return ("pending",)
                st.cond.wait(remaining)

    def _op_stream_consumed(self, seed, index):
        self.runtime.stream_consumed(seed, index)
        return True

    # -- actors

    def _op_create_actor(self, cls_fn_id, pickled_cls, args_payload, deps,
                         opts, locations, actor_id_b=None, nonce=None,
                         owner=None, gcs_epoch_seq=None):
        # GCS-driven restarts stamp their epoch_seq; a fenced (stale)
        # head's restart must not run — it would fork actor state
        self._check_gcs_epoch(gcs_epoch_seq)
        return self._dedup(nonce, lambda: self._do_create_actor(
            cls_fn_id, pickled_cls, args_payload, deps, opts, locations,
            actor_id_b, owner))

    def _do_create_actor(self, cls_fn_id, pickled_cls, args_payload, deps,
                         opts, locations, actor_id_b=None, owner=None):
        rt = self.runtime
        if (owner is not None and actor_id_b is not None
                and (opts or {}).get("lifetime") != "detached"):
            with self._owner_lock:
                self._actor_owner[actor_id_b] = owner
        if pickled_cls is not None:
            with rt._lock:
                rt._functions.setdefault(cls_fn_id, pickled_cls)
        for b in deps:
            self.ensure_available(
                b, hint=tuple(locations[b]) if locations and b in locations
                else None, priority=PRIO_TASK_ARGS)
        actor_id = rt._create_actor_from_payload(
            cls_fn_id, args_payload, [ObjectID(b) for b in deps],
            dict(opts or {}),
            actor_id=ActorID(actor_id_b) if actor_id_b else None)
        return actor_id.binary()

    def _op_actor_call(self, actor_id_bytes, method, args_payload, deps,
                       nested, return_ids, nonce=None, owner=None,
                       stream=False, opts=None):
        return self._dedup(nonce, lambda: self._do_actor_call(
            actor_id_bytes, method, args_payload, deps, nested, return_ids,
            owner, stream, opts))

    def _do_actor_call(self, actor_id_bytes, method, args_payload, deps,
                       nested, return_ids, owner=None, stream=False,
                       opts=None):
        rt = self.runtime
        if owner is not None:
            self._tag_owner(return_ids, owner)
        actor_id = ActorID(actor_id_bytes)
        state = rt._actors.get(actor_id)
        if state is None:
            raise ActorDiedError(f"actor {actor_id} is not on this node")
        # bounded restart window: past the buffer cap / restart deadline
        # this raises ActorUnavailableError, which travels back through
        # the RPC layer typed (callers must see "may come back", never a
        # hang and never a premature death)
        rt._check_actor_admission(state)
        for b in deps:
            self.ensure_available(b, priority=PRIO_TASK_ARGS)
        for b in nested:
            self.ensure_available(b, priority=PRIO_TASK_ARGS)
        ret_ids = [ObjectID(b) for b in return_ids]
        for rid in ret_ids:
            rt._entry(rid)
        task_id = make_task_id(rt.job_id)
        if stream:
            # register before the dead check so ActorDiedError routes
            # through _fail_stream rather than landing on the seed id
            rt._register_stream(ret_ids[0].binary())
        if state.dead:
            if state.migrated:
                # planned-drain eviction: the actor lives on elsewhere —
                # reject at submit so the caller re-routes through the
                # actor_state channel instead of consuming a dead result
                raise ActorDiedError(
                    f"actor {actor_id} migrated off this node")
            rt._store_error(ret_ids, rt._actor_dead_error(state))
            return True
        spec = _TaskSpec(task_id, None, args_payload,
                         [ObjectID(b) for b in deps], ret_ids,
                         dict(opts or {}), actor_id=actor_id, method=method)
        spec.nested_deps = [ObjectID(b) for b in nested]
        if stream:
            spec.stream = rt._stream_opts(ret_ids[0].binary())
        rt._cancellable[ret_ids[0].binary()] = spec
        rt._enqueue(spec)
        return True

    def _op_actor_opts(self, actor_id_bytes):
        return self.runtime.get_actor_method_opts(ActorID(actor_id_bytes))

    def _op_prestart_workers(self, num: int):
        """Backlog hint: pre-fork idle workers ahead of a burst
        (reference: PrestartWorkers RPC, raylet/worker_pool.h:344)."""
        self.runtime.prestart_workers(int(num))
        return True

    def _op_kill_actor(self, actor_id_bytes, no_restart,
                       gcs_epoch_seq=None):
        # a stale head reaping an actor it believes dead would kill a
        # healthy incarnation the NEW head is tracking
        self._check_gcs_epoch(gcs_epoch_seq)
        self.runtime.kill_actor(ActorID(actor_id_bytes), no_restart=no_restart)
        return True

    def _op_evict_actor(self, actor_id_bytes, gcs_epoch_seq=None,
                        wait_s=0.5):
        # drain migration: same fencing as kill_actor, but the reap
        # waits for in-flight calls to settle and fails nothing
        self._check_gcs_epoch(gcs_epoch_seq)
        return self.runtime.evict_actor(ActorID(actor_id_bytes),
                                        wait_s=wait_s)

    # -- placement groups (node-local; the driver composes cluster PGs)

    def _op_pg(self, op, *args):
        rt = self.runtime
        if op == "create":
            bundles, strategy, name = args
            pg = rt.create_placement_group(bundles, strategy, name)
            return pg.id.binary()
        if op == "table":
            # no pg-id operand — must dispatch before the id parse below
            # (the autoscaler polls this for pending-PG demand)
            return rt.placement_group_table()
        pg_id = PlacementGroupID(args[0])
        if op == "wait":
            return rt.wait_placement_group(pg_id, args[1])
        if op == "remove":
            rt.remove_placement_group(pg_id)
            return True
        if op == "chips":
            return rt.placement_group_chips(pg_id, args[1])
        if op == "table":
            return rt.placement_group_table()
        raise ValueError(f"unknown pg op {op!r}")

    # -- lifecycle

    def _op_shutdown_node(self):
        def drain_and_exit():
            self.close()
            if self._owns_process:
                # a drained node must actually release its process (the
                # autoscaler's cloud view polls liveness): lingering
                # non-daemon helper threads would otherwise pin it
                os._exit(0)

        threading.Thread(target=drain_and_exit, daemon=True).start()
        return True

    def close(self):
        if self._stop:
            return
        self._stop = True
        self.gcs.try_call(("unregister_node", self.node_id.binary()))
        self._server.close()
        try:
            self.runtime.shutdown()
        # rtpu-lint: disable=L4 — node teardown: whatever state the
        # runtime is in, the peers and GCS client still get closed
        except Exception:  # noqa: BLE001
            pass
        self._peers.close_all()
        self.gcs.close()


def _parse_addr(s: str) -> Tuple[str, int]:
    host, port = s.rsplit(":", 1)
    return host, int(port)


def main(argv=None):
    import argparse
    import signal
    import sys

    p = argparse.ArgumentParser(description="ray_tpu node server")
    p.add_argument("--gcs", required=True, help="GCS address host:port")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-workers", type=int, default=None)
    p.add_argument("--object-store-memory", type=int, default=None)
    p.add_argument("--resources", type=str, default=None,
                   help='JSON dict of extra resources, e.g. {"disk": 2}')
    p.add_argument("--head", action="store_true",
                   help="run head-node services (job agent)")
    args = p.parse_args(argv)
    resources = None
    if args.resources:
        import json

        resources = json.loads(args.resources)
    node = NodeServer(_parse_addr(args.gcs), num_workers=args.num_workers,
                      object_store_memory=args.object_store_memory,
                      resources=resources, port=args.port)
    node._owns_process = True
    agent = None
    if args.head:
        from ray_tpu.job.agent import JobAgent

        agent = JobAgent(node.gcs, _parse_addr(args.gcs),
                         agent_id=node.node_id.hex())
    print(f"NODE_ADDRESS {node.address[0]}:{node.address[1]}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())

    def _dump_stacks(*_a):
        # ops hatch (mirrors the workers' SIGUSR1 dumps): all-thread
        # stacks of the NODE SERVER itself, to a file — stderr may be
        # detached under a supervisor
        import traceback

        path = f"/tmp/rtpu_node_stacks_{os.getpid()}.txt"
        with open(path, "w") as f:
            for tid, fr in sys._current_frames().items():
                f.write(f"--- thread {tid} ---\n")
                f.write("".join(traceback.format_stack(fr)))

    signal.signal(signal.SIGUSR2, _dump_stacks)
    stop.wait()
    if agent is not None:
        agent.close()
    node.close()
    sys.exit(0)


if __name__ == "__main__":
    main()
