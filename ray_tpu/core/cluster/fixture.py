"""Cluster fixture: a multi-node cluster of real processes on one host.

The capability analogue of the reference's ``cluster_utils.Cluster``
(python/ray/cluster_utils.py:135): start a GCS + N node-server processes,
connect a driver, add/remove nodes mid-test. Each node is a full separate
process (own shm store, own worker pool) talking real TCP — the same code
path a multi-host deployment uses, just colocated.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu.core.cluster.rpc import RpcClient, cluster_authkey, pick_port


def _read_tagged_line(proc: subprocess.Popen, tag: str, timeout: float = 30.0
                      ) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"process exited ({proc.returncode}) before printing "
                    f"{tag}: {proc.stderr.read() if proc.stderr else ''}")
            time.sleep(0.01)
            continue
        line = line.decode() if isinstance(line, bytes) else line
        if line.startswith(tag):
            return line[len(tag):].strip()
    raise TimeoutError(f"timed out waiting for {tag}")


def _parse_addr(s: str) -> Tuple[str, int]:
    host, port = s.rsplit(":", 1)
    return host, int(port)


class NodeProc:
    """A node-server subprocess handle."""

    def __init__(self, proc: subprocess.Popen, address: Tuple[str, int]):
        self.proc = proc
        self.address = address

    def kill(self):
        """Hard-kill the node (simulates node failure)."""
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()


class Cluster:
    """Start/stop a local multi-node cluster.

    Usage::

        cluster = Cluster(num_nodes=3, num_workers_per_node=2)
        core = cluster.connect()        # a ClusterCore bound to this cluster
        ...
        cluster.shutdown()
    """

    def __init__(self, num_nodes: int = 1, num_workers_per_node: int = 2,
                 object_store_memory: int = 128 << 20,
                 node_resources: Optional[List[dict]] = None,
                 env: Optional[Dict[str, str]] = None,
                 gcs_persist_dir: Optional[str] = None):
        self.authkey = os.urandom(16)
        self._env = dict(os.environ)
        self._env["RTPU_CLUSTER_AUTHKEY"] = self.authkey.hex()
        # node processes must not inherit a TPU claim; workers are CPU-side
        self._env.update(env or {})
        self.procs: List[subprocess.Popen] = []
        self.nodes: List[NodeProc] = []
        self._store_mem = object_store_memory
        self._nw = num_workers_per_node
        self._gcs_persist_dir = gcs_persist_dir

        # netem rules armed via partition()/gray(): (src endpoint,
        # src selector, dst selector, kind); heal() clears exactly these
        self._partitions: List[Tuple[object, str, str, str]] = []

        self._gcs_port = pick_port()
        self._start_gcs()

        for i in range(num_nodes):
            res = None
            if node_resources and i < len(node_resources):
                res = node_resources[i]
            self.add_node(resources=res)

    def _start_gcs(self):
        cmd = [sys.executable, "-m", "ray_tpu.core.cluster.gcs",
               "--port", str(self._gcs_port)]
        if self._gcs_persist_dir:
            cmd += ["--persist-dir", self._gcs_persist_dir]
        self._gcs_proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=self._env)
        self.procs.append(self._gcs_proc)
        self.gcs_address = _parse_addr(
            _read_tagged_line(self._gcs_proc, "GCS_ADDRESS "))

    def kill_gcs(self):
        """Hard-kill the GCS process (chaos: control-plane failure)."""
        if self._gcs_proc.poll() is None:
            self._gcs_proc.kill()
            self._gcs_proc.wait()
        if self._gcs_proc in self.procs:
            self.procs.remove(self._gcs_proc)

    def gcs_alive(self) -> bool:
        """Whether the GCS subprocess is still running (chaos tests use
        this to observe a fault-injected self-kill, e.g. gcs_kill)."""
        return self._gcs_proc.poll() is None

    def wait_gcs_dead(self, timeout: float = 30.0) -> bool:
        """Block until the GCS subprocess exits (e.g. an armed gcs_kill
        site fired). Reaps the handle so restart_gcs can follow."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._gcs_proc.poll() is not None:
                if self._gcs_proc in self.procs:
                    self.procs.remove(self._gcs_proc)
                return True
            time.sleep(0.02)
        return False

    def restart_gcs(self, env_overrides: Optional[Dict[str, str]] = None):
        """Restart the GCS on the SAME port (requires gcs_persist_dir for
        state to survive); nodes re-register on their next heartbeat.
        ``env_overrides`` mutate the cluster env for the new process — a
        value of None deletes the var (e.g. disarm an RTPU_FAULT_* spec
        that already fired so the restarted head doesn't re-arm it)."""
        self.kill_gcs()
        for k, v in (env_overrides or {}).items():
            if v is None:
                self._env.pop(k, None)
            else:
                self._env[k] = v
        self._start_gcs()

    def add_node(self, num_workers: Optional[int] = None,
                 resources: Optional[dict] = None) -> NodeProc:
        cmd = [sys.executable, "-m", "ray_tpu.core.cluster.node_server",
               "--gcs", f"{self.gcs_address[0]}:{self.gcs_address[1]}",
               "--num-workers", str(num_workers or self._nw),
               "--object-store-memory", str(self._store_mem)]
        if resources:
            cmd += ["--resources", json.dumps(resources)]
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, env=self._env)
        self.procs.append(proc)
        addr = _parse_addr(_read_tagged_line(proc, "NODE_ADDRESS "))
        node = NodeProc(proc, addr)
        self.nodes.append(node)
        return node

    def remove_node(self, node: NodeProc, graceful: bool = False):
        """Remove a node; ungraceful kill exercises failure detection."""
        if graceful:
            try:
                # rtpu-lint: disable=L9 — test-fixture teardown: whether
                # the shutdown RPC applied is moot, kill() below ends
                # the process unconditionally
                RpcClient(node.address, self.authkey, connect_timeout=2.0
                          ).call(("shutdown_node",))
            # rtpu-lint: disable=L4 — graceful is best-effort: the node
            # often closes the connection mid-reply while shutting down;
            # kill() below is the guaranteed path either way
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.2)
        node.kill()
        if node in self.nodes:
            self.nodes.remove(node)

    def wait_for_nodes(self, count: Optional[int] = None,
                       timeout: float = 30.0) -> bool:
        client = RpcClient(self.gcs_address, self.authkey)
        try:
            return client.call(("wait_nodes", count or len(self.nodes),
                                timeout))
        finally:
            client.close()

    # ------------------------------------------------ network chaos (netem)

    def _netem_addr(self, ep) -> Optional[Tuple[str, int]]:
        """Resolve a partition endpoint to its listen address: "gcs",
        "driver" (no listen address — nothing dials the driver), a
        NodeProc, or an explicit (host, port) tuple."""
        if ep == "gcs":
            return self.gcs_address
        if ep == "driver":
            return None
        if isinstance(ep, NodeProc):
            return ep.address
        return tuple(ep)

    def _netem_ctl(self, ep, cmd: str, *args):
        """Deliver one netem control op to an endpoint's process. The
        driver is this process (in-process call); nodes and the GCS get
        a ``("netem", ...)`` RPC over their (unaffected) control edge."""
        from ray_tpu.core import netem

        if ep == "driver":
            return netem.control(cmd, *args)
        addr = self._netem_addr(ep)
        client = RpcClient(addr, self.authkey, connect_timeout=5.0)
        try:
            return client.call(("netem", cmd) + args)
        finally:
            client.close()

    def partition(self, a, b, oneway: bool = False):
        """Sever the network edge a -> b (and b -> a unless ``oneway``)
        by arming client-side netem partition rules in the source
        process(es). Endpoints: "gcs", "driver", a NodeProc, or an
        address tuple. Reversed by heal()."""
        for src, dst in ((a, b),) if oneway else ((a, b), (b, a)):
            dst_addr = self._netem_addr(dst)
            if dst_addr is None:
                continue  # nothing dials the driver: no inbound edge
            dst_sel = f"{dst_addr[0]}:{dst_addr[1]}"
            self._netem_ctl(src, "add", "*", dst_sel, "partition", {})
            self._partitions.append((src, "*", dst_sel, "partition"))

    def gray(self, node: "NodeProc", ms: float = 300.0,
             jitter: float = 300.0, p: float = 0.05):
        """Make ``node`` a gray-failing node: every RPC it SENDS (its
        heartbeats included) takes drop probability ``p`` plus
        ``ms`` + U(0, ``jitter``) of delay — alive on the control plane,
        flaky on the wire. The GCS health scorer should QUARANTINE it
        while healthy nodes stay ALIVE. Reversed by heal()."""
        self._netem_ctl(node, "add", "*", "*", "delay",
                        {"ms": ms, "jitter": jitter})
        self._partitions.append((node, "*", "*", "delay"))
        if p > 0:
            self._netem_ctl(node, "add", "*", "*", "drop", {"p": p})
            self._partitions.append((node, "*", "*", "drop"))

    def heal(self):
        """Clear every netem rule armed through partition()/gray().
        Best-effort per endpoint: a process that died mid-chaos is
        skipped. Driver-sourced rules clear FIRST — they live in this
        process and can sever the very control edges the remote clears
        dial over (e.g. partition(driver, node) + partition(node, gcs):
        the node's rule is cleared via an RPC the driver's own rule
        would block)."""
        parts, self._partitions = self._partitions, []
        parts.sort(key=lambda p: p[0] != "driver")
        for src, src_sel, dst_sel, kind in parts:
            try:
                self._netem_ctl(src, "clear", src_sel, dst_sel, kind)
            # rtpu-lint: disable=L4 — heal is teardown-adjacent: a dead
            # endpoint can't hold a partition rule anyway
            except Exception:  # noqa: BLE001
                pass

    # --------------------------------------------- drain / lifecycle

    def _node_id_of(self, node: "NodeProc") -> bytes:
        client = RpcClient(self.gcs_address, self.authkey)
        try:
            listing = client.call(("list_nodes", False))
        finally:
            client.close()
        for n in listing["nodes"]:
            if tuple(n["address"]) == tuple(node.address):
                return n["node_id"]
        raise KeyError(f"node {node.address} not in the GCS table")

    def drain(self, node: "NodeProc") -> bool:
        """Begin planned removal of ``node`` (ALIVE -> DRAINING)."""
        node_id = self._node_id_of(node)
        client = RpcClient(self.gcs_address, self.authkey)
        try:
            return bool(client.call(("drain_node", node_id)))
        finally:
            client.close()

    def node_state(self, node: "NodeProc") -> Optional[str]:
        """The GCS lifecycle state of ``node`` (None once deregistered)."""
        client = RpcClient(self.gcs_address, self.authkey)
        try:
            listing = client.call(("list_nodes", False))
        finally:
            client.close()
        for n in listing["nodes"]:
            if tuple(n["address"]) == tuple(node.address):
                return n["state"]
        return None

    def wait_node_state(self, node: "NodeProc", state: str,
                        timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.node_state(node) == state:
                return True
            time.sleep(0.05)
        return False

    def connect(self):
        """A ClusterCore driver bound to this cluster (also installs it as
        the process-wide core so the public API routes through it)."""
        from ray_tpu.core import runtime_context
        from ray_tpu.core.cluster.cluster_core import ClusterCore

        core = ClusterCore(self.gcs_address, authkey=self.authkey)
        runtime_context.set_core(core)
        return core

    def disconnect(self):
        from ray_tpu.core import runtime_context

        core = runtime_context.get_core_or_none()
        if core is not None:
            core.shutdown()
        runtime_context.set_core(None)

    def shutdown(self):
        self.disconnect()
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 5
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.wait(max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
        self.procs.clear()
        self.nodes.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
