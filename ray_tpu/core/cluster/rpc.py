"""TCP RPC substrate for the cluster plane.

``multiprocessing.connection`` over AF_INET gives framed pickling plus an
HMAC authkey handshake; on top of that this module provides a threaded
request/response server and a pooled client. This fills the role gRPC plays
in the reference (src/ray/rpc/grpc_server.h) at single-digit-node scale;
the wire format is an implementation detail hidden behind RpcClient/serve.

Blocking RPCs (e.g. a get that waits for a task) hold one pooled connection
for their duration; the pool grows on demand and idles out.

Retry semantics are NOT decided here: ``WIRE_CONTRACT`` in
``protocol_meta.py`` is the single source of truth classifying every wire
op as idempotent / retry-after-apply / dedup-keyed / non-retryable, and
``_retry_safe_after_apply`` below merely consults the sets derived from it.
"""

from __future__ import annotations

import os
import random
import socket
import threading
import time
from multiprocessing.connection import Client as _MpClient  # noqa: F401
from multiprocessing.connection import Connection as _MpConnection
from multiprocessing.connection import answer_challenge, deliver_challenge
from typing import Any, Callable, List, Optional, Tuple

from ray_tpu.core import netem
from ray_tpu.core.cluster import protocol_meta
from ray_tpu.core.config import config
from ray_tpu.util.debug_lock import make_lock


class RpcError(Exception):
    """Transport-level RPC failure (peer died, connection refused).

    ``maybe_applied`` is True when the request made it onto the wire but
    the reply was lost, the op is not on the retry-after-apply whitelist,
    and the server may therefore have applied it once already — blind
    replay would risk running the side effect twice. False means the
    request either never reached the server or is safe to re-send.
    """

    maybe_applied: bool = False


class RemoteError(Exception):
    """Application-level error raised by the remote handler."""


def _timed_handshake(conn, authkey: bytes, *, server_side: bool,
                     timeout: Optional[float] = None):
    """Run the HMAC challenge with a hard deadline.

    ``multiprocessing``'s challenge reads have NO timeout; worse, its
    Listener runs the handshake inside ``accept()``, so one half-open
    connection (a peer that connected and then stalled or died silently)
    wedges the single accept loop and every subsequent connection to the
    server hangs in ``answer_challenge`` forever — observed as node
    fetch threads stuck mid-connect while pooled connections kept
    working. A watchdog closes the connection at the deadline, which
    unblocks the in-flight read with EOF/OSError.

    The default deadline is the ``rpc_handshake_timeout_s`` flag, so
    partition tests can shrink it cluster-wide through the env.
    """
    if timeout is None:
        timeout = config.rpc_handshake_timeout_s
    done = threading.Event()

    def watchdog():
        if not done.wait(timeout):
            # closing the fd does NOT unblock a read already parked in
            # another thread on Linux; shutdown() on the shared file
            # description does (the read returns EOF)
            try:
                s = socket.socket(fileno=os.dup(conn.fileno()))
                try:
                    s.shutdown(socket.SHUT_RDWR)
                finally:
                    s.close()
            except (OSError, ValueError):
                # handshake already finished and closed the conn under us
                # (fileno on a closed Connection) — nothing left to unblock
                pass
            try:
                conn.close()
            except OSError:
                pass

    threading.Thread(target=watchdog, daemon=True,
                     name="rpc-handshake-wd").start()
    try:
        if server_side:
            deliver_challenge(conn, authkey)
            answer_challenge(conn, authkey)
        else:
            answer_challenge(conn, authkey)
            deliver_challenge(conn, authkey)
    finally:
        done.set()


def pick_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _ReuseAddrListener:
    """``multiprocessing.connection.Listener`` equivalent (same framed
    ``Connection`` objects) over a SO_REUSEADDR socket: a server
    restarted on the SAME port — the GCS failover path — must not lose
    the bind to a predecessor connection lingering in TIME_WAIT."""

    def __init__(self, address: Tuple[str, int]):
        self._sock = socket.create_server(address, backlog=128)
        self.address = self._sock.getsockname()

    def accept(self):
        s, _ = self._sock.accept()
        s.setblocking(True)
        return _MpConnection(s.detach())

    def close(self):
        # shutdown() first: close() alone does not release the socket
        # while the accept thread is parked in accept() (the in-flight
        # syscall pins the open file description, which would keep the
        # port bound and fail a same-port successor)
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


class RpcServer:
    """Threaded request/response server.

    handler(msg, ctx) -> reply. Exceptions in the handler are shipped back
    and re-raised client-side as RemoteError (or the original exception when
    picklable). ``ctx`` is a per-connection dict handlers may use to stash
    state (e.g. peer identity after a hello message).
    """

    def __init__(self, handler: Callable[[Any, dict], Any],
                 authkey: bytes, host: str = "127.0.0.1", port: int = 0):
        self._handler = handler
        self._authkey = authkey
        if port == 0:
            port = pick_port()
        # NO authkey on the listener: accept() must return immediately
        # after the TCP accept; the HMAC handshake runs (bounded) in the
        # per-connection thread — see _timed_handshake
        self._listener = _ReuseAddrListener((host, port))
        self.address: Tuple[str, int] = (host, port)
        self._stop = False
        # live accepted connections, severed on close(): the per-conn
        # threads are parked in recv() and would otherwise keep serving
        # a "closed" server until the process exits
        self._conns: set = set()
        self._conns_lock = make_lock("RpcServer._conns_lock")
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rpc-accept")
        self._accept_thread.start()

    def _accept_loop(self):
        while not self._stop:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError, Exception):  # noqa: BLE001
                if self._stop:
                    return
                continue
            # daemon threads, never joined — don't retain references
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="rpc-conn").start()

    def _serve_conn(self, conn):
        ctx: dict = {}
        with self._conns_lock:
            if self._stop:
                try:
                    conn.close()
                except OSError:
                    pass
                return
            self._conns.add(conn)
        try:
            self._serve_conn_inner(conn, ctx)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)

    def _serve_conn_inner(self, conn, ctx):
        try:
            _timed_handshake(conn, self._authkey, server_side=True)
        # rtpu-lint: disable=L4 — any handshake failure (bad key, stall,
        # peer death, watchdog-forced EOF) means the same thing: drop the
        # connection; the server must survive arbitrary garbage from peers
        except Exception:  # noqa: BLE001 — bad key / stalled / died
            try:
                conn.close()
            except OSError:
                pass
            return
        try:
            while not self._stop:
                msg = conn.recv()
                if netem.enabled():
                    # at=server rules: inbound delay sleeps here; an
                    # inbound fault raises NetemFault (an OSError),
                    # severing this connection mid-exchange — the peer
                    # observes a sent-but-unanswered request
                    netem.plan_dispatch()
                try:
                    reply = ("ok", self._handler(msg, ctx))
                except BaseException as e:  # noqa: BLE001
                    reply = ("exc", e)
                try:
                    conn.send(reply)
                except (EOFError, OSError):
                    raise
                except Exception:  # noqa: BLE001 — unpicklable payload/exc:
                    # degrade to a picklable error instead of killing the
                    # connection (which clients would misread as node death)
                    conn.send(("exc", RemoteError(
                        f"unpicklable {'error' if reply[0] == 'exc' else 'reply'}: "
                        f"{reply[1]!r}")))
        except (EOFError, OSError):
            pass
        finally:
            on_close = ctx.get("on_close")
            if on_close is not None:
                try:
                    on_close()
                # rtpu-lint: disable=L4 — on_close is an arbitrary
                # handler-registered callback; a buggy one must not take
                # down the connection teardown path with it
                except Exception:  # noqa: BLE001
                    pass
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        self._stop = True
        try:
            self._listener.close()
        except OSError:
            pass
        # sever live connections: their serve threads are parked in
        # recv() and would otherwise keep answering pooled clients after
        # "close" (a process kill severs them; in-process close must too)
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                s = socket.socket(fileno=conn.fileno())
                try:
                    s.shutdown(socket.SHUT_RDWR)
                finally:
                    s.detach()
            except OSError:
                pass


# Ops that are safe to retry after the request may have been APPLIED once
# (reply lost: send succeeded, recv failed): every op WIRE_CONTRACT in
# protocol_meta.py classifies as a read, a set-style write where
# apply-twice == apply-once, or dedup-keyed exactly-once. That table is
# the single source of truth — classify new ops THERE, never here; the
# L9 lint rule rejects retry paths that disagree with it.
# The reference splits the same way: gRPC retries are enabled per-method
# only for idempotent GCS reads (src/ray/rpc/gcs_server/gcs_rpc_client.h).
_IDEMPOTENT_OPS = protocol_meta.RETRY_SAFE_OPS

_IDEMPOTENT_KV_SUBOPS = protocol_meta.RETRY_SAFE_KV_SUBOPS


def _retry_safe_after_apply(msg) -> bool:
    """True when re-sending ``msg`` is safe even if the server already
    applied it once (at-least-once delivery is indistinguishable from
    exactly-once for these ops)."""
    try:
        op = msg[0]
    except Exception:  # noqa: BLE001
        return False
    if op == "kv":
        return len(msg) > 1 and msg[1] in _IDEMPOTENT_KV_SUBOPS
    return op in _IDEMPOTENT_OPS


class RpcClient:
    """Pooled client to one RpcServer address.

    Thread-safe: each call checks out a connection (creating one if the pool
    is dry), does one request/response round trip, and returns it.
    """

    def __init__(self, address: Tuple[str, int], authkey: bytes,
                 connect_timeout: float = 10.0,
                 unavailable_exc: Optional[type] = None):
        self.address = tuple(address)
        self._authkey = authkey
        self._timeout = connect_timeout
        # Exception type raised when connect retries exhaust (must accept
        # a single message argument). Lets GCS clients surface a typed
        # GcsUnavailableError while plain node clients keep RpcError.
        self._unavailable_exc = unavailable_exc or RpcError
        self._pool: List[Any] = []
        self._lock = make_lock("RpcClient._lock")
        self._closed = False
        # bumped whenever an established connection failed and we dialed
        # again: lets wrappers (HaGcsClient) notice a server restart that
        # the in-call reconnect absorbed without surfacing any error
        self.reconnects = 0

    def _connect(self):
        deadline = time.monotonic() + self._timeout
        delay = 0.02
        while True:
            try:
                # connect WITHOUT authkey, then run the bounded
                # handshake ourselves — a wedged/half-dead server must
                # not hang this thread forever (see _timed_handshake)
                conn = _MpClient(self.address)
                try:
                    _timed_handshake(conn, self._authkey,
                                     server_side=False)
                except Exception as he:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    from multiprocessing import AuthenticationError
                    if isinstance(he, AuthenticationError):
                        # deterministic: retrying a wrong key only
                        # hammers the server until the deadline
                        raise RpcError(
                            f"authentication rejected by "
                            f"{self.address}: {he}") from he
                    raise OSError(
                        f"authkey handshake with {self.address[0]}:"
                        f"{self.address[1]} failed/timed out "
                        f"(rpc_handshake_timeout_s="
                        f"{config.rpc_handshake_timeout_s:g})")
                return conn
            except (ConnectionRefusedError, OSError) as e:
                if time.monotonic() >= deadline:
                    raise self._unavailable_exc(
                        f"cannot connect to {self.address}: {e}") from e
                # Exponential backoff with full jitter: a restarting
                # server sees the whole cluster reconnect at once, and
                # synchronized retries stampede its accept loop.
                time.sleep(min(delay * random.random() + 0.005,
                               max(deadline - time.monotonic(), 0.005)))
                delay = min(delay * 2, 0.5)

    def call(self, msg: Any) -> Any:
        with self._lock:
            if self._closed:
                raise RpcError("client closed")
            conn = self._pool.pop() if self._pool else None
        if conn is None:
            conn = self._connect()
        sent = False
        try:
            # Netem weave: a fault rule (drop/partition/blackhole)
            # raises NetemFault — an OSError — BEFORE any bytes move,
            # landing in the sent=False safe-retry arm below exactly
            # like a refused connect; "dup" double-sends the request on
            # this pipelined connection (the server applies it twice,
            # back-to-back); "lost_reply" raises AFTER the send so the
            # sent=True / maybe_applied machinery is exercised for real.
            plan = netem.plan_send(self.address, msg) \
                if netem.enabled() else None
            conn.send(msg)
            if plan == "dup":
                conn.send(msg)
            sent = True
            if plan == "lost_reply":
                raise netem.NetemFault(
                    f"netem lost_reply: reply from {self.address[0]}:"
                    f"{self.address[1]} discarded")
            tag, value = conn.recv()
            if plan == "dup":
                conn.recv()  # drain the duplicate's reply
        except (EOFError, OSError, BrokenPipeError) as e:
            try:
                conn.close()
            except OSError:
                pass
            # same-address retry: a pooled connection that fails almost
            # certainly died while parked (server restart) — drop the
            # whole pool (parked siblings share its fate); a fresh
            # connection that fails mid-exchange gets one more try too,
            # so a lost REPLY is retried on the SAME server, where nonce
            # dedup (node_server._dedup) makes re-delivery exactly-once.
            # Retry is only safe when the request cannot have been
            # applied (send itself failed — partial frames are discarded
            # server-side) OR the op is retry-safe per the whitelist; a
            # lost reply to anything else surfaces as RpcError, never
            # re-runs side effects (at-least-once hazard).
            if not sent or _retry_safe_after_apply(msg):
                with self._lock:
                    stale, self._pool = self._pool, []
                    self.reconnects += 1
                for c in stale:
                    try:
                        c.close()
                    except OSError:
                        pass
                conn = self._connect()
                sent2 = False
                try:
                    # the retry passes through netem too: a partition
                    # blocks the built-in same-address retry as well,
                    # so the caller sees a fast typed failure instead
                    # of an accidental escape hatch around the chaos
                    plan2 = netem.plan_send(self.address, msg) \
                        if netem.enabled() else None
                    conn.send(msg)
                    if plan2 == "dup":
                        conn.send(msg)
                    sent2 = True
                    if plan2 == "lost_reply":
                        raise netem.NetemFault(
                            f"netem lost_reply: reply from "
                            f"{self.address[0]}:{self.address[1]} "
                            f"discarded")
                    tag, value = conn.recv()
                    if plan2 == "dup":
                        conn.recv()  # drain the duplicate's reply
                except (EOFError, OSError, BrokenPipeError) as e2:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    err2 = RpcError(
                        f"rpc to {self.address} failed: {e2}")
                    err2.maybe_applied = (
                        sent2 and not _retry_safe_after_apply(msg))
                    raise err2 from e2
            else:
                err = RpcError(f"rpc to {self.address} failed: {e}")
                err.maybe_applied = True  # sent and not retry-safe
                raise err from e
        with self._lock:
            if self._closed:
                conn.close()
            else:
                self._pool.append(conn)
        if tag == "exc":
            raise value
        return value

    def try_call(self, msg: Any, default=None):
        """call() that swallows transport errors (for best-effort releases)."""
        try:
            return self.call(msg)
        except RpcError:
            return default

    def close(self):
        with self._lock:
            self._closed = True
            pool, self._pool = self._pool, []
        for conn in pool:
            try:
                conn.close()
            except OSError:
                pass


class ClientCache:
    """Process-wide cache of RpcClients keyed by address."""

    def __init__(self, authkey: bytes):
        self._authkey = authkey
        self._clients = {}
        self._lock = make_lock("ClientCache._lock")

    def get(self, address: Tuple[str, int]) -> RpcClient:
        address = tuple(address)
        with self._lock:
            c = self._clients.get(address)
            if c is None:
                c = self._clients[address] = RpcClient(address, self._authkey)
            return c

    def drop(self, address: Tuple[str, int]):
        with self._lock:
            c = self._clients.pop(tuple(address), None)
        if c is not None:
            c.close()

    def close_all(self):
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()


def cluster_authkey() -> bytes:
    """The cluster session authkey (hex in RTPU_CLUSTER_AUTHKEY).

    There is deliberately no default: the transport deserializes pickles,
    so a well-known key would hand any local user code execution in the
    cluster processes. Every launcher (Cluster fixture, CLI) generates a
    random key and passes it via the environment."""
    key = os.environ.get("RTPU_CLUSTER_AUTHKEY")
    if key:
        return bytes.fromhex(key)
    raise RuntimeError(
        "RTPU_CLUSTER_AUTHKEY is not set. Generate one (e.g. "
        "`python -c \"import os; print(os.urandom(16).hex())\"`) and export "
        "it identically in every cluster process.")
