"""Multi-node substrate: GCS control plane, per-node servers, TCP RPC.

The reference splits these across gcs_server (src/ray/gcs/gcs_server/),
raylet (src/ray/raylet/) and the object manager
(src/ray/object_manager/object_manager.h) talking gRPC; here the same
capabilities ride a framed-pickle TCP transport (rpc.py) and each node
embeds the single-node Runtime as its local scheduler.
"""

from ray_tpu.core.cluster.fixture import Cluster  # noqa: F401
