"""GCS — the cluster control plane.

One process holding the authoritative cluster state, mirroring the
reference's gcs_server (src/ray/gcs/gcs_server/gcs_server.h:78) at the
capability level:

- node table + health: registration, periodic heartbeats with resource
  loads, a monitor thread that marks silent nodes DEAD and records a death
  event stream (reference: gcs_node_manager.h:45,
  gcs_health_check_manager.h:39)
- named actor directory (gcs_actor_manager)
- size-tracked object location directory with blocking waits (the
  reference spreads this across the ownership layer + object directory;
  here the GCS is the rendezvous so any node can find any object's
  owner). ``loc_add``/``loc_add_batch`` optionally carry ``nbytes`` so
  the directory doubles as a size table; ``loc_get_batch`` resolves many
  ids in one RPC (non-blocking) and returns ``{oid: (addrs, nbytes)}``
  for the driver's locality-aware scheduler
- cluster KV (gcs_kv_manager) and a cluster function table
  (function_manager.py exports to GCS in the reference)

Run as ``python -m ray_tpu.core.cluster.gcs --port N``.

Wire semantics of every ``_op_*`` arm here — may a client re-send it
after a lost reply, and how does its state resync after failover — are
declared in ``WIRE_CONTRACT``/``RESYNC_COVERAGE`` (protocol_meta.py),
the single source of truth the transport whitelist derives from. Add a
new op there first; the L9/L10 lint rules fail on unclassified arms,
on persisted tables missing from ``_WAL_OPS``/the snapshot round-trip,
and on nondeterminism inside WAL-replayed apply bodies.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import fault_injection, netem
from ray_tpu.core.cluster.rpc import RpcServer, cluster_authkey
from ray_tpu.core.config import config
from ray_tpu.exceptions import StaleGcsEpochError
from ray_tpu.util.debug_lock import make_lock

# ops whose effects must survive a GCS restart (heartbeats and reads are
# deliberately not logged: transient / no effect). kv is logged only for
# its mutating sub-ops — see _WAL_KV_MUTATORS.
_WAL_OPS = frozenset({
    "register_node", "unregister_node", "kv", "name_actor",
    "drop_actor_name", "register_actor", "register_actor_spec",
    "drop_actor_spec", "loc_add", "loc_add_batch",
    "loc_drop", "freed_add", "publish", "register_fn",
    "drain_node", "node_drained",
})

# node lifecycle: ALIVE -> DRAINING -> DRAINED (planned removal, clean
# deregistration) and ALIVE <-> QUARANTINED (gray-failure cordon). Only
# ALIVE nodes are schedulable; DRAINING/QUARANTINED/DRAINED nodes keep
# heartbeating (their data plane stays up) but receive no new work.
_LIVE_STATES = ("ALIVE", "DRAINING", "QUARANTINED")
_WAL_KV_MUTATORS = frozenset({"put", "del", "merge", "cas_merge"})
_WAL_SNAPSHOT_EVERY = 50_000  # records between compactions


class _NodeInfo:
    __slots__ = ("node_id", "address", "resources", "topology", "labels",
                 "state", "last_heartbeat", "avail", "load", "death_seq",
                 "drain_deadline", "jitter_ewma", "fail_total", "fail_ewma",
                 "clean_since", "last_probe")

    def __init__(self, node_id: bytes, address, resources, topology, labels):
        self.node_id = node_id
        self.address = tuple(address)
        self.resources = dict(resources)       # total resources
        self.topology = topology               # TPU topology summary (dict)
        self.labels = dict(labels or {})
        self.state = "ALIVE"
        self.last_heartbeat = time.monotonic()
        self.avail = dict(resources)           # latest reported availability
        self.load = 0                          # queued+running tasks
        self.death_seq = None
        # drain: absolute monotonic deadline for the grace window
        self.drain_deadline = None
        # gray-failure health signals (EWMAs updated per heartbeat):
        # jitter = excess heartbeat interval over the expected cadence,
        # fail = per-tick unexpected worker-death delta. fail_total is
        # the last cumulative counter the node reported.
        self.jitter_ewma = 0.0
        self.fail_total = 0
        self.fail_ewma = 0.0
        # quarantine hysteresis: when the score first dropped below the
        # recovery threshold (None while still dirty) and the last time
        # the un-quarantine probe pinged this node
        self.clean_since = None
        self.last_probe = 0.0

    def view(self) -> dict:
        return {
            "node_id": self.node_id,
            "address": self.address,
            "resources": self.resources,
            "topology": self.topology,
            "labels": self.labels,
            "state": self.state,
            "avail": self.avail,
            "load": self.load,
        }


class GcsServer:
    """In-process GCS server (embed in a dedicated process via main()).

    With ``persistence_path`` set, every state-mutating op is written to a
    write-ahead log before the reply, compacted into a snapshot
    periodically; a restarted GCS on the same path rehydrates
    nodes/actors/KV/locations/functions/tombstones and resumes pubsub seq
    counters, so subscribers resync through the normal seq-gap path and
    nodes re-register on their next rejected heartbeat (reference:
    src/ray/gcs/store_client/redis_store_client.h:33 — the role of the
    Redis-backed table storage, done as a single-writer WAL instead of an
    external store). Durability: appends are flush()ed (survives GCS
    process crash); set ``RTPU_GCS_WAL_FSYNC=1`` to fsync per append and
    additionally survive host/OS crashes."""

    # L7 lock-protection intent for fields whose majority-use lock is
    # NOT their guard:
    # - _pdir: persistence dir path, write-once in __init__, immutable.
    # - _epoch: incarnation marker, write-once in __init__, immutable.
    # - _wal: the BINDING doubles as the "persistence enabled" flag —
    #   set before serving starts and nulled once at close(); readers
    #   probe it lock-free by design (lock order forbids _wal_lock under
    #   self._lock). The file CONTENTS are serialized by _wal_lock.
    # - _epoch_seq: monotonic incarnation counter, write-once in
    #   __init__, immutable.
    # - _fenced / _fenced_by: one-way False->True split-brain latch.
    #   Writers hold self._lock; the per-op dispatch check in _handle
    #   reads it lock-free by design (a latch read can only be one op
    #   late, and taking self._lock on every dispatch would tax the
    #   hot path for a test-of-time rarity).
    _guarded_by_ = {"_pdir": None, "_epoch": None, "_wal": None,
                    "_epoch_seq": None, "_fenced": None,
                    "_fenced_by": None}

    def __init__(self, port: int = 0, authkey: Optional[bytes] = None,
                 persistence_path: Optional[str] = None):
        self._authkey = authkey or cluster_authkey()
        self._peers = None  # lazy ClientCache for actor-restart RPCs
        # restartable/detached actor specs: the GCS owns the restart FSM
        # (reference: gcs_actor_manager.h:278) so actors outlive drivers
        self._actor_specs: Dict[bytes, dict] = {}
        self._lock = make_lock("GcsServer._lock")
        self._cond = threading.Condition(self._lock)
        self._nodes: Dict[bytes, _NodeInfo] = {}
        # condensed peer_health suspicion reports, keyed by reporter
        # node_id -> {"host:port": recent-failure streak}; folded into
        # the per-node health score (transient — not persisted)
        self._peer_reports: Dict[bytes, Dict[str, int]] = {}
        self._next_orphan_scan = 0.0  # health-loop cadence (monotonic)
        self._kv: Dict[str, Any] = {}
        self._named_actors: Dict[str, Tuple[bytes, tuple]] = {}
        self._actor_table: Dict[bytes, dict] = {}
        self._locations: Dict[bytes, List[tuple]] = {}
        # object sizes (bytes), keyed like _locations and sharing its
        # lifecycle: entries die when the last location drops. Sizes feed
        # the driver's locality scorer; None/absent means "unknown".
        self._obj_sizes: Dict[bytes, int] = {}
        self._functions: Dict[bytes, bytes] = {}
        self._deaths: List[Tuple[int, bytes]] = []  # (seq, node_id)
        self._death_seq = 0
        # driver (owner) registry: drivers heartbeat like nodes; a dead
        # driver's objects/actors are reclaimed cluster-wide (reference:
        # job death handling, gcs_job_manager.h — owner-failure semantics
        # of reference_count.h:61 done GCS-mediated)
        self._drivers: Dict[bytes, float] = {}     # driver_id -> last hb
        self._driver_deaths: List[Tuple[int, bytes]] = []
        self._driver_death_seq = 0
        # pubsub channels: bounded event logs with long-poll subscribers
        # (reference: src/ray/pubsub/publisher.h:296)
        self._channels: Dict[str, List[Tuple[int, Any]]] = {}
        self._channel_seq: Dict[str, int] = {}
        # eager-free tombstones (worker-originated frees): bounded,
        # insertion-ordered — consulted before any fetch-retry spin or
        # lineage reconstruction so "free means dead" holds cluster-wide
        self._freed: Dict[bytes, None] = {}
        self._view_version = 0
        self._stop = False
        # Incarnation marker: minted fresh per GCS process, never
        # persisted. Clients compare it across replies to detect that the
        # head restarted (even a fast restart between two heartbeats) and
        # trigger a full resync (reference: gcs_server session_name).
        self._epoch = os.urandom(8).hex()
        # Split-brain fencing latch: set when evidence arrives that a
        # NEWER GCS incarnation exists (a node reported a higher
        # epoch_seq, or rejected one of our writes with
        # StaleGcsEpochError). A fenced head stops restarting actors,
        # stops marking deaths, and rejects mutating ops — the random
        # _epoch above detects restarts, the monotonic _epoch_seq
        # (minted below, after persistence) ORDERS incarnations.
        self._fenced = False
        self._fenced_by = 0  # newest epoch_seq that fenced us
        # RECOVERING window: a restart that rehydrated prior state gives
        # known nodes/drivers this long to heartbeat back in before the
        # health loop may declare them DEAD (set in _load_persisted).
        self._recovering_until = 0.0
        # persistence: rehydrate BEFORE serving so no request sees
        # pre-recovery state. LOCK ORDER: _wal_lock, then self._lock —
        # mutating ops apply-and-log atomically under _wal_lock (the op
        # body takes self._lock inside), and compaction snapshots the same
        # way, so WAL order always matches apply order and no inversion
        # exists. Code holding self._lock must never take _wal_lock
        # (deaths buffer into _wal_pending instead).
        self._wal = None
        self._wal_lock = make_lock("GcsServer._wal_lock")
        self._wal_pending: List[tuple] = []  # guarded by self._lock
        self._wal_count = 0
        self._replaying = False
        self._pdir = persistence_path
        if persistence_path:
            os.makedirs(persistence_path, exist_ok=True)
            self._replaying = True
            self._load_persisted()
            self._replaying = False
            self._wal = open(os.path.join(persistence_path, "wal.pkl"), "ab")
        self._epoch_seq = self._mint_epoch_seq()
        self._server = RpcServer(self._handle, self._authkey, port=port)
        self.address = self._server.address
        netem.set_identity("gcs", self.address)
        self._monitor = threading.Thread(target=self._health_loop,
                                         daemon=True, name="gcs-health")
        self._monitor.start()

    def _mint_epoch_seq(self) -> int:
        """A strictly increasing incarnation number. With a persist dir
        it is a durable counter file (incremented per incarnation, so
        any two heads sharing the dir are totally ordered); without one
        a millisecond timestamp still orders incarnations across
        processes well enough for fencing tests."""
        if self._pdir:
            path = os.path.join(self._pdir, "epoch_seq")
            try:
                with open(path, encoding="utf-8") as f:
                    prev = int(f.read().strip() or 0)
            except (OSError, ValueError):
                prev = 0
            seq = prev + 1
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(str(seq))
            os.replace(tmp, path)
            return seq
        return int(time.time() * 1000)

    # ------------------------------------------------------- persistence

    def _snapshot_state(self) -> dict:
        with self._lock:
            return {
                "nodes": [(i.node_id, i.address, i.resources, i.topology,
                           i.labels, i.state) for i in self._nodes.values()],
                "kv": dict(self._kv),
                "named_actors": dict(self._named_actors),
                "actor_table": {k: dict(v)
                                for k, v in self._actor_table.items()},
                "locations": {k: list(v)
                              for k, v in self._locations.items()},
                "obj_sizes": dict(self._obj_sizes),
                "functions": dict(self._functions),
                "actor_specs": {k: dict(v)
                                for k, v in self._actor_specs.items()},
                "freed": dict(self._freed),
                "deaths": list(self._deaths),
                "death_seq": self._death_seq,
                "driver_deaths": list(self._driver_deaths),
                "driver_death_seq": self._driver_death_seq,
                "channel_seq": dict(self._channel_seq),
                "channels": {k: list(v) for k, v in self._channels.items()},
                "view_version": self._view_version,
            }

    def _restore_state(self, s: dict):
        # startup path (before the RPC server and health monitor exist),
        # but cheap to hold the lock anyway — so the guarded-field
        # invariant is uniform instead of "except during restore"
        with self._lock:
            for node_id, address, resources, topology, labels, state in \
                    s.get("nodes", []):
                info = _NodeInfo(node_id, address, resources, topology,
                                 labels)
                info.state = state
                if state == "DRAINING":
                    # re-arm the grace window: the pre-crash deadline was
                    # monotonic (meaningless across processes), and the
                    # node reports node_drained itself when it goes idle
                    info.drain_deadline = (time.monotonic()
                                           + config.node_drain_grace_s)
                # ALIVE nodes get a fresh grace period: the health monitor
                # re-marks truly-dead ones after the heartbeat timeout,
                # live ones heartbeat in (and re-register if they were
                # marked DEAD during the outage)
                self._nodes[node_id] = info
            self._kv = dict(s.get("kv", {}))
            self._named_actors = dict(s.get("named_actors", {}))
            self._actor_table = {k: dict(v)
                                 for k, v in s.get("actor_table",
                                                   {}).items()}
            self._locations = {k: list(map(tuple, v))
                               for k, v in s.get("locations", {}).items()}
            self._obj_sizes = dict(s.get("obj_sizes", {}))
            self._functions = dict(s.get("functions", {}))
            self._actor_specs = {k: dict(v)
                                 for k, v in s.get("actor_specs",
                                                   {}).items()}
            self._freed = dict(s.get("freed", {}))
            self._deaths = [tuple(d) for d in s.get("deaths", [])]
            self._death_seq = s.get("death_seq", 0)
            self._driver_deaths = [tuple(d)
                                   for d in s.get("driver_deaths", [])]
            self._driver_death_seq = s.get("driver_death_seq", 0)
            self._channel_seq = dict(s.get("channel_seq", {}))
            self._channels = {k: [tuple(e) for e in v]
                              for k, v in s.get("channels", {}).items()}
            self._view_version = s.get("view_version", 0) + 1

    def _load_persisted(self):
        snap_path = os.path.join(self._pdir, "snapshot.pkl")
        wal_path = os.path.join(self._pdir, "wal.pkl")
        # a crash mid-compaction can strand the temp file; the real
        # snapshot (if any) is intact because os.replace is atomic
        try:
            os.unlink(snap_path + ".tmp")
        except OSError:
            pass
        recovered = False
        if os.path.exists(snap_path):
            with open(snap_path, "rb") as f:
                self._restore_state(pickle.load(f))
            recovered = True
        if os.path.exists(wal_path):
            recovered = recovered or os.path.getsize(wal_path) > 0
            with open(wal_path, "rb") as f:
                while True:
                    try:
                        op, args = pickle.load(f)
                    # rtpu-lint: disable=L4 — a torn tail record from a
                    # crash mid-append can surface as EOFError,
                    # UnpicklingError, or (truncated frame/garbage bytes)
                    # ValueError/AttributeError and others; any failure to
                    # decode the NEXT record means the log ends here
                    except Exception:  # noqa: BLE001
                        break  # torn tail record from a crash: stop here
                    try:
                        if op == "__death__":
                            with self._lock:
                                info = self._nodes.get(args[0])
                                if info is not None \
                                        and info.state == "ALIVE":
                                    self._mark_dead_locked(info)
                        elif op == "__driver_death__":
                            # keep the seq monotonic across restarts so
                            # nodes' watermarks stay valid (spec drops
                            # replay via their own records)
                            with self._lock:
                                self._driver_death_seq += 1
                                self._driver_deaths.append(
                                    (self._driver_death_seq, args[0]))
                        else:
                            getattr(self, "_op_" + op)(*args)
                    # rtpu-lint: disable=L4 — WAL replay is best-effort:
                    # one corrupt/stale record (schema drift across a
                    # version bump, truncated tail write) must not keep
                    # the whole GCS from starting
                    except Exception:  # noqa: BLE001
                        continue
        if recovered:
            self._recovering_until = (time.monotonic()
                                      + config.gcs_recovery_grace_s)

    def _wal_write_locked(self, op: str, args: tuple):
        """Append one record (+ any buffered death records); _wal_lock
        held by the caller."""
        with self._lock:
            pending, self._wal_pending = self._wal_pending, []
        for rec in pending:
            pickle.dump(rec, self._wal)
            self._wal_count += 1
        if op is not None:
            pickle.dump((op, args), self._wal)
            self._wal_count += 1
        self._wal.flush()
        if config.gcs_wal_fsync:
            os.fsync(self._wal.fileno())
        if self._wal_count >= _WAL_SNAPSHOT_EVERY:
            self._compact_locked()

    def _flush_pending_deaths(self):
        """Health-loop hook: persist buffered __death__ records. Runs
        WITHOUT self._lock so the _wal_lock -> self._lock order holds."""
        # rtpu-lint: disable=L7 — deliberate lock-free emptiness probe:
        # a stale read only delays the flush one health-loop tick; the
        # authoritative swap happens under self._lock in
        # _wal_write_locked
        if self._wal is None or not self._wal_pending:
            return
        with self._wal_lock:
            self._wal_write_locked(None, ())

    def _compact_locked(self):
        """Snapshot current state, truncate the WAL (wal lock held; the
        snapshot takes self._lock inside — consistent lock order)."""
        snap_path = os.path.join(self._pdir, "snapshot.pkl")
        tmp = snap_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self._snapshot_state(), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, snap_path)
        # fsync the directory too: the rename itself must be durable, or
        # a host crash can resurrect the old snapshot with a truncated WAL
        dfd = os.open(self._pdir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._wal.close()
        self._wal = open(os.path.join(self._pdir, "wal.pkl"), "wb")
        self._wal_count = 0

    # ------------------------------------------------------------ health

    def _health_loop(self):
        timeout = config.gcs_heartbeat_timeout_s
        drv_timeout = config.driver_heartbeat_timeout_s
        while not self._stop:
            time.sleep(min(0.1, timeout / 4))
            now = time.monotonic()
            if self._fenced:
                # a newer head exists: marking deaths from this side of
                # the partition would fork cluster state (the classic
                # split-brain write) — stand down until killed
                continue
            if now < self._recovering_until:
                # RECOVERING: we just rehydrated from snapshot+WAL and the
                # whole cluster is reconnecting — declaring anything DEAD
                # on a stale last_heartbeat now would cascade restarts for
                # nodes that are merely mid-reconnect
                self._flush_pending_deaths()
                continue
            probe_targets = []
            with self._lock:
                for info in list(self._nodes.values()):
                    if (info.state in _LIVE_STATES
                            and now - info.last_heartbeat > timeout):
                        self._mark_dead_locked(info)
                    elif (info.state == "DRAINING"
                            and info.drain_deadline is not None
                            and now >= info.drain_deadline):
                        # grace window over: whatever was still running
                        # had its chance — declare the drain complete so
                        # the node can deregister cleanly
                        self._apply_drained_locked(info)
                        if self._wal is not None:
                            self._wal_pending.append(
                                ("node_drained", (info.node_id,)))
                for did, last in list(self._drivers.items()):
                    if now - last > drv_timeout:
                        self._mark_driver_dead_locked(did)
                probe_targets = self._quarantine_scan_locked(now)
            self._flush_pending_deaths()
            if probe_targets:
                self._probe_quarantined(probe_targets)
            if now >= self._next_orphan_scan:
                self._next_orphan_scan = now + max(
                    0.1, config.job_lease_ttl_s / 4)
                self._scan_orphan_jobs()

    def _mark_dead_locked(self, info: _NodeInfo):
        # timeout-detected deaths are state too (explicit unregisters are
        # WAL'd as their own op). self._lock is held: BUFFER the record —
        # the health loop flushes it after releasing the lock (lock order
        # forbids taking _wal_lock here).
        if self._wal is not None:
            self._wal_pending.append(("__death__", (info.node_id,)))
        self._peer_reports.pop(info.node_id, None)
        info.drain_deadline = None
        info.state = "DEAD"
        self._death_seq += 1
        info.death_seq = self._death_seq
        self._deaths.append((self._death_seq, info.node_id))
        self._publish_locked("node_deaths", {
            "node_id": info.node_id, "address": list(info.address)})
        self._view_version += 1
        # objects whose only location was the dead node are now lost
        dead_addr = info.address
        for oid, locs in list(self._locations.items()):
            locs = [a for a in locs if a != dead_addr]
            if locs:
                self._locations[oid] = locs
            else:
                del self._locations[oid]
                self._obj_sizes.pop(oid, None)
        # GCS-owned actor restart (reference: gcs_actor_manager.h:278 —
        # the FSM lives HERE so named/detached actors survive driver exit
        # and node death alike)
        # NOT during WAL replay: a replayed death is history — if the
        # actor was since restarted, later WAL records already say where
        # it lives; if its host truly died during the outage, the health
        # monitor re-detects that death after the grace period and this
        # path fires then, on live state.
        lost = [aid for aid, spec in self._actor_specs.items()
                if tuple((self._actor_table.get(aid) or {})
                         .get("node", ())) == dead_addr]
        if lost and not self._stop and not self._replaying:
            threading.Thread(target=self._restart_actors, args=(lost,),
                             daemon=True, name="gcs-actor-restart").start()
        self._cond.notify_all()

    # --------------------------------------- drain / quarantine lifecycle

    def _apply_drained_locked(self, info: _NodeInfo):
        """DRAINING -> DRAINED (self._lock held). The node's data plane
        stays up (objects remain fetchable) but it is out of every
        scheduling pool; its eventual unregister is the quiet path — no
        death event, no lineage reconstruction. Callers that reach this
        from the health loop must buffer the ``node_drained`` WAL record
        themselves (the RPC path is logged by _handle)."""
        if info.state != "DRAINING":
            return
        info.state = "DRAINED"
        info.drain_deadline = None
        self._publish_locked("node_state", {
            "node_id": info.node_id, "address": list(info.address),
            "state": "DRAINED"})
        self._view_version += 1
        self._cond.notify_all()

    def _quarantine_scan_locked(self, now: float) -> List[tuple]:
        """Score every live node and flip gray ones to QUARANTINED
        (self._lock held). Returns the [(node_id, address)] of
        quarantined nodes due for an un-quarantine liveness probe —
        probing is an RPC, so the caller does it after releasing the
        lock.

        Score = heartbeat-jitter EWMA + worker-death-rate EWMA + peer
        suspicion. Suspicion sums the recent-failure streaks other nodes
        report about this one (capped per reporter), discounted by the
        reporter's OWN jitter/failure score — a node that is itself gray
        cannot quarantine its healthy peers by blaming them for its own
        flaky edges."""
        thr = config.quarantine_score_threshold
        if thr <= 0:
            return []
        probes: List[tuple] = []
        for info in self._nodes.values():
            if info.state not in ("ALIVE", "QUARANTINED"):
                continue
            addr_str = f"{info.address[0]}:{info.address[1]}"
            susp = 0.0
            for rid, reports in self._peer_reports.items():
                if rid == info.node_id:
                    continue
                streak = reports.get(addr_str, 0)
                if streak <= 0:
                    continue
                reporter = self._nodes.get(rid)
                own = (reporter.jitter_ewma + reporter.fail_ewma
                       if reporter is not None else 0.0)
                susp += min(streak, 5) / (1.0 + own)
            score = info.jitter_ewma + info.fail_ewma + susp
            if info.state == "ALIVE":
                if score >= thr:
                    info.state = "QUARANTINED"
                    info.clean_since = None
                    self._publish_locked("node_state", {
                        "node_id": info.node_id,
                        "address": list(info.address),
                        "state": "QUARANTINED", "score": score})
                    self._view_version += 1
                    self._cond.notify_all()
                continue
            # QUARANTINED: hysteresis — the score must stay below half
            # the threshold for quarantine_recover_s AND the node must
            # answer a liveness probe before it rejoins the pool
            if score >= thr / 2:
                info.clean_since = None
                continue
            if info.clean_since is None:
                info.clean_since = now
            if (now - info.clean_since >= config.quarantine_recover_s
                    and now - info.last_probe
                    >= max(0.1, config.quarantine_recover_s / 2)):
                info.last_probe = now
                probes.append((info.node_id, info.address))
        return probes

    def _probe_quarantined(self, targets: List[tuple]):
        """Liveness-probe quarantined nodes whose score has stayed clean
        through the hysteresis window; a successful ping restores them
        to ALIVE. Runs WITHOUT self._lock (it is an RPC)."""
        from ray_tpu.core.cluster.rpc import RpcError

        self._ensure_peers()
        for node_id, address in targets:
            try:
                self._peers.get(tuple(address)).call(("ping",))
            except (RpcError, OSError):
                continue
            with self._lock:
                info = self._nodes.get(node_id)
                if info is None or info.state != "QUARANTINED" \
                        or info.clean_since is None:
                    continue
                info.state = "ALIVE"
                info.clean_since = None
                info.jitter_ewma = 0.0
                info.fail_ewma = 0.0
                self._publish_locked("node_state", {
                    "node_id": node_id, "address": list(info.address),
                    "state": "ALIVE"})
                self._view_version += 1
                self._cond.notify_all()

    def _ensure_peers(self):
        from ray_tpu.core.cluster.rpc import ClientCache

        if self._peers is None:
            self._peers = ClientCache(self._authkey)

    # ------------------------------------------- supervised-job orphans

    def _scan_orphan_jobs(self):
        """Re-queue (or fail, per max_restarts policy) RUNNING jobs whose
        agent lease expired — a SIGKILLed agent can no longer strand
        them. Candidates are collected under self._lock; the mutation
        itself is a WAL'd cas_merge keyed on the exact expired lease, so
        a racing agent renewal (or a concurrent scan on another thread)
        safely loses."""
        from ray_tpu.job.backoff import delay_for

        now = time.time()
        with self._lock:
            candidates = [(key, dict(spec), spec.get("lease_expires_at"))
                          for key, spec in self._kv.items()
                          if key.startswith("job/")
                          and isinstance(spec, dict)
                          and spec.get("status") == "RUNNING"
                          and spec.get("lease_expires_at")
                          and spec["lease_expires_at"] < now]
        for key, spec, lease in candidates:
            expect = {"status": "RUNNING", "lease_expires_at": lease}
            restarts = int(spec.get("restarts") or 0)
            max_restarts = int(spec.get("max_restarts") or 0)
            if spec.get("stop_requested"):
                # stop semantics hold across the orphan boundary: the
                # agent died before honoring the stop — finish the job
                # as STOPPED instead of resurrecting it
                updates = {"status": "STOPPED", "lease_expires_at": None,
                           "agent": None,
                           "message": "stopped (agent lost)"}
            elif restarts < max_restarts:
                bo = spec.get("backoff") or {}
                delay = delay_for(spec.get("submission_id") or key,
                                  restarts, bo.get("base_s", 1.0),
                                  bo.get("max_s", 30.0))
                updates = {"status": "PENDING", "agent": None,
                           "restarts": restarts + 1,
                           "next_eligible_at": now + delay,
                           "lease_expires_at": None, "orphaned": True,
                           "backoff_history":
                               list(spec.get("backoff_history") or [])
                               + [delay],
                           "message": "orphaned (agent lease expired); "
                                      "re-queued"}
            else:
                updates = {"status": "FAILED", "lease_expires_at": None,
                           "agent": None,
                           "message": "job agent lost (lease expired)"}
            self._kv_mutate_internal("cas_merge", key, (expect, updates))

    def _kv_mutate_internal(self, op: str, key: str, value=None):
        """A GCS-originated kv mutation with the same apply+log
        discipline _handle gives client ops (callers must NOT hold
        self._lock — lock order is _wal_lock then self._lock)."""
        if self._wal is not None:
            with self._wal_lock:
                result = self._op_kv(op, key, value)
                self._wal_write_locked("kv", (op, key, value))
            return result
        return self._op_kv(op, key, value)

    # ----------------------------------------------- actor restart FSM

    def _restart_actors(self, actor_ids: List[bytes],
                        timeout: float = 300.0, migrate_from=None):
        """Restart (node death) or migrate (``migrate_from`` = the
        draining node's address) the given actors. Migration rides the
        same FSM but is free: no restart-budget charge, no terminal
        branch at budget 0 — the actor is healthy, its host is merely
        being retired — and the live copy is evicted first so exactly
        one incarnation ever runs."""
        from ray_tpu.core.cluster.rpc import RpcError

        self._ensure_peers()
        for aid in actor_ids:
            with self._lock:
                if self._fenced:
                    return  # stale head: a newer incarnation owns the FSM
                spec = self._actor_specs.get(aid)
            if spec is None:
                continue
            opts = dict(spec.get("opts") or {})
            restarts = int(opts.get("max_restarts", 0))
            detached = opts.get("lifetime") == "detached"
            if migrate_from is None:
                if restarts == 0 and not detached:
                    # budget exhausted: terminal — subscribers must fail
                    # buffered calls with ActorDiedError, not keep waiting
                    with self._lock:
                        self._actor_table.setdefault(
                            aid, {})["state"] = "DEAD"
                        self._publish_actor_state_locked(aid, "DEAD", spec,
                                                         opts)
                    continue
                if restarts > 0:
                    opts["max_restarts"] = restarts - 1
            with self._lock:
                self._publish_actor_state_locked(aid, "RESTARTING", spec,
                                                 opts)
            if migrate_from is not None:
                # planned drain: quiesce-then-reap the live copy before
                # the new one exists — queued and in-flight calls finish
                # (bounded by the drain grace), nothing is failed, and
                # exactly one incarnation ever runs. Past the grace the
                # reap turns forceful: the window is a promise to the
                # cluster, not to one chatty actor.
                try:
                    peer = self._peers.get(tuple(migrate_from))
                    grace = time.monotonic() + config.node_drain_grace_s
                    # rtpu-lint: disable=L9 — deliberate poll-until-done
                    # loop, and the op is epoch-fenced (_epoch_seq): a
                    # duplicate eviction of an already-evicted actor is
                    # a no-op, a stale epoch is rejected by the node
                    while not peer.call(("evict_actor", aid,
                                         self._epoch_seq, 0.5)):
                        if time.monotonic() >= grace or self._stop:
                            peer.call(("kill_actor", aid, True,
                                       self._epoch_seq))
                            break
                except StaleGcsEpochError as fe:
                    with self._lock:
                        self._fenced = True
                        self._fenced_by = max(self._fenced_by,
                                              fe.current_seq)
                    return
                except (RpcError, OSError):
                    pass  # node gone mid-drain: death path takes over
            deadline = time.monotonic() + timeout
            nonce = os.urandom(16)
            restarted = False
            while time.monotonic() < deadline and not self._stop:
                addr = self._pick_restart_node(opts)
                if addr is None:
                    time.sleep(0.5)  # pend until a fitting node joins
                    continue
                with self._lock:
                    pickled = self._functions.get(spec["cls_fn_id"])
                try:
                    # one nonce per restart invocation: a lost reply is
                    # retried same-node by the transport and deduped
                    # there; later restarts of the same actor mint their
                    # own nonce. An RpcError reaching HERE means the node
                    # was unreachable even after the same-node retry, so
                    # re-picking a node is right; a create that applied
                    # on a PARTITIONED (not dead) node can still leave a
                    # stale copy — at-least-once under partition, like
                    # the reference's actor restart.
                    self._peers.get(addr).call(
                        ("create_actor", spec["cls_fn_id"], pickled,
                         spec["payload"], list(spec.get("deps") or []),
                         opts, None, aid, nonce, spec.get("owner"),
                         self._epoch_seq))
                except StaleGcsEpochError as fe:
                    # the node has seen a NEWER head: we are the stale
                    # half of a split brain — fence ourselves and stop
                    # writing (the new incarnation owns the restart FSM)
                    with self._lock:
                        self._fenced = True
                        self._fenced_by = max(self._fenced_by,
                                              fe.current_seq)
                    return
                except RpcError:
                    time.sleep(0.5)
                    continue
                # apply + log atomically under _wal_lock (same discipline
                # as _handle) so a concurrent drop_actor_spec can never
                # slot between our apply and our log — replay order must
                # equal apply order or a replayed WAL resurrects a spec
                # that was dropped
                dropped = False
                with self._wal_lock:
                    with self._lock:
                        dropped = aid not in self._actor_specs
                        if not dropped:
                            self._actor_specs[aid] = dict(spec, opts=opts)
                            self._actor_table.setdefault(aid, {}).update(
                                {"node": addr, "state": "RESTARTED"})
                            name = spec.get("name")
                            if name and self._named_actors.get(
                                    name, (None,))[0] == aid:
                                self._named_actors[name] = (aid, addr)
                            self._publish_actor_state_locked(
                                aid, "ALIVE", spec, opts, node=addr)
                            restarted = True
                    if not dropped and self._wal is not None:
                        self._wal_write_locked(
                            "register_actor",
                            (aid, {"node": addr, "state": "RESTARTED"}))
                        self._wal_write_locked(
                            "register_actor_spec",
                            (aid, dict(spec, opts=opts)))
                if dropped:
                    # the actor was killed (drop_actor_spec) while our
                    # create was in flight: reap the copy we just created
                    # or it runs orphaned, holding resources forever
                    try:
                        self._peers.get(addr).call(
                            ("kill_actor", aid, True, self._epoch_seq))
                    except StaleGcsEpochError as fe:
                        with self._lock:
                            self._fenced = True
                            self._fenced_by = max(self._fenced_by,
                                                  fe.current_seq)
                        return
                    except RpcError:
                        pass
                break
            if not restarted:
                # dropped mid-restart or no node materialized before the
                # deadline: terminal either way from the callers' view
                with self._lock:
                    self._actor_table.setdefault(aid, {})["state"] = "DEAD"
                    self._publish_actor_state_locked(aid, "DEAD", spec, opts)

    def _publish_actor_state_locked(self, aid: bytes, state: str,
                                    spec: dict, opts: dict, node=None):
        """One actor-restart FSM transition on the ``actor_state``
        channel (same shape the single-node runtime publishes, so driver
        subscribers handle both sources with one code path)."""
        self._publish_locked("actor_state", {
            "actor_id": aid,
            "state": state,
            "restarts_left": int(opts.get("max_restarts", 0)),
            "name": spec.get("name"),
            "node": list(node) if node else None,
        })

    def _pick_restart_node(self, opts: dict):
        """An ALIVE node whose TOTAL resources cover the request (the
        node's own queue pends the creation if currently busy)."""
        req: Dict[str, float] = {}
        if opts.get("num_cpus"):
            req["CPU"] = float(opts["num_cpus"])
        if opts.get("num_tpus"):
            req["TPU"] = float(opts["num_tpus"])
        for k, v in (opts.get("resources") or {}).items():
            req[k] = req.get(k, 0) + float(v)
        with self._lock:
            fit = [i for i in self._nodes.values() if i.state == "ALIVE"
                   and all(i.resources.get(k, 0) >= v
                           for k, v in req.items())]
        if not fit:
            return None
        fit.sort(key=lambda i: i.load)
        return fit[0].address

    # ------------------------------------------------------------ handler

    def _handle(self, msg, ctx) -> Any:
        op = msg[0]
        if fault_injection.enabled():
            # chaos site: SIGKILL the head mid-request, deterministically
            # keyed by op name (arm e.g. RTPU_FAULT_GCS_KILL=kill:1:kv to
            # die while handling the first kv op)
            if fault_injection.fire("gcs_kill", op) == "kill":
                os.kill(os.getpid(), 9)  # SIGKILL — no cleanup, no WAL flush
        fn = getattr(self, "_op_" + op, None)
        if fn is None:
            raise ValueError(f"unknown GCS op {op!r}")
        if (self._fenced and op in _WAL_OPS
                and (op != "kv" or msg[1] in _WAL_KV_MUTATORS)):
            # stale-writer rejection, server side: once fenced, every
            # state-mutating op gets the typed error — a client still
            # talking to this head must fail over to the new one, not
            # write into a fork
            raise StaleGcsEpochError(
                f"GCS mutation {op!r} rejected: this head is fenced",
                stale_seq=self._epoch_seq, current_seq=self._fenced_by)
        if (self._wal is not None and op in _WAL_OPS
                and (op != "kv" or msg[1] in _WAL_KV_MUTATORS)):
            # apply + log atomically: concurrent mutators serialize here,
            # so replay order always equals apply order
            with self._wal_lock:
                result = fn(*msg[1:])
                self._wal_write_locked(op, tuple(msg[1:]))
            return result
        return fn(*msg[1:])

    # -- nodes

    def _op_register_node(self, node_id: bytes, address, resources,
                          topology, labels=None):
        with self._lock:
            prev = self._nodes.get(node_id)
            # rtpu-lint: disable=L10 — _NodeInfo stamps last_heartbeat
            # with time.monotonic(): transient liveness state, NOT
            # replayed table data. Replay MUST grant a fresh grace
            # window — replaying the original wall-clock stamp would
            # declare every node dead the moment the health loop runs
            # (the recovery grace in _load_persisted depends on this).
            info = _NodeInfo(node_id, address, resources, topology, labels)
            if prev is not None and prev.state in ("DRAINING",
                                                   "QUARANTINED"):
                # a resync re-register must not launder a cordoned node
                # back into the scheduling pool
                info.state = prev.state
                info.drain_deadline = prev.drain_deadline
                info.jitter_ewma = prev.jitter_ewma
                info.fail_ewma = prev.fail_ewma
            self._nodes[node_id] = info
            self._view_version += 1
            self._cond.notify_all()
        return True

    def _op_heartbeat(self, node_id: bytes, avail: dict, load: int,
                      seen_epoch_seq: int = 0, stats: dict = None):
        # replies carry the GCS epoch so nodes detect a head restart even
        # when every heartbeat is accepted (persisted state restored the
        # node as ALIVE) and resync their locations/actors/PGs; they also
        # carry epoch_seq (fencing order), the freed-channel head so a
        # node can cheaply notice frees it missed while partitioned, and
        # the node's lifecycle state so a DRAINING node starts winding
        # down. ``stats`` (optional) feeds the gray-failure scorer:
        # {"task_failures": cumulative worker-death count,
        #  "peer_health": {"host:port": recent-failure streak}}.
        with self._lock:
            if seen_epoch_seq and seen_epoch_seq > self._epoch_seq:
                # the node has heartbeated a NEWER incarnation: this
                # head is the stale side of a split brain — fence
                self._fenced = True
                self._fenced_by = max(self._fenced_by, seen_epoch_seq)
            base = {"epoch": self._epoch, "epoch_seq": self._epoch_seq,
                    "fenced": self._fenced,
                    "freed_head": self._channel_seq.get("freed", 0)}
            info = self._nodes.get(node_id)
            if self._fenced or info is None or info.state == "DEAD":
                # node must re-register (or, fenced: go away entirely)
                return dict(base, accepted=False)
            now = time.monotonic()
            expected = max(1e-3, config.gcs_heartbeat_interval_s)
            # excess interval ratio over 1.5x the cadence (clamped so one
            # huge gap cannot poison the EWMA forever)
            excess = max(0.0, (now - info.last_heartbeat) / expected - 1.5)
            info.jitter_ewma = (0.7 * info.jitter_ewma
                                + 0.3 * min(excess, 10.0))
            info.last_heartbeat = now
            if stats:
                failures = int(stats.get("task_failures") or 0)
                delta = max(0, failures - info.fail_total)
                info.fail_total = failures
                info.fail_ewma = (0.7 * info.fail_ewma
                                  + 0.3 * min(delta, 10.0))
                peer = stats.get("peer_health")
                if peer:
                    self._peer_reports[node_id] = dict(peer)
                else:
                    self._peer_reports.pop(node_id, None)
            if info.avail != avail or info.load != load:
                info.avail = dict(avail)
                info.load = load
                # rtpu-lint: disable=L10 — _view_version is a monotonic
                # cache-invalidation counter, not table data: it is
                # persisted only so a restore resumes PAST every seen
                # value (+1 in _restore_state); losing heartbeat bumps
                # to compaction timing can never roll a client backward
                self._view_version += 1
            state = info.state
        return dict(base, accepted=True, state=state)

    def _op_unregister_node(self, node_id: bytes):
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None:
                return True
            if info.state == "DRAINED":
                # clean deregistration: the drain already migrated the
                # actors and let running work finish, so this is NOT a
                # death — no event on node_deaths, no restart FSM, no
                # lineage reconstruction storm. Its remaining locations
                # drop quietly (consumers fetched during the grace).
                del self._nodes[node_id]
                self._peer_reports.pop(node_id, None)
                dead_addr = info.address
                for oid, locs in list(self._locations.items()):
                    kept = [a for a in locs if a != dead_addr]
                    if kept:
                        self._locations[oid] = kept
                    else:
                        del self._locations[oid]
                        self._obj_sizes.pop(oid, None)
                self._publish_locked("node_state", {
                    "node_id": node_id, "address": list(info.address),
                    "state": "REMOVED"})
                self._view_version += 1
                self._cond.notify_all()
            elif info.state in _LIVE_STATES:
                self._mark_dead_locked(info)
        return True

    def _op_drain_node(self, node_id: bytes):
        """Begin planned removal: ALIVE/QUARANTINED -> DRAINING. The
        scheduler cordon is immediate (only ALIVE nodes are placement
        candidates); restartable/detached actors migrate via the restart
        FSM; running tasks get ``node_drain_grace_s`` to finish before
        the health loop forces DRAINED (the node reports node_drained
        itself as soon as it goes idle)."""
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None or info.state == "DEAD":
                return False
            if info.state in ("DRAINING", "DRAINED"):
                return True  # idempotent: re-drain is a no-op
            info.state = "DRAINING"
            # rtpu-lint: disable=L10 — drain_deadline is transient
            # pacing (monotonic clock is meaningless across processes):
            # replay and _restore_state both deliberately re-arm a
            # FRESH grace window; the durable fact is only the DRAINING
            # state itself
            info.drain_deadline = (time.monotonic()
                                   + config.node_drain_grace_s)
            self._publish_locked("node_state", {
                "node_id": node_id, "address": list(info.address),
                "state": "DRAINING"})
            self._view_version += 1
            addr = info.address
            moving = [aid for aid, spec in self._actor_specs.items()
                      if tuple((self._actor_table.get(aid) or {})
                               .get("node", ())) == addr]
            self._cond.notify_all()
        if moving and not self._stop and not self._replaying:
            threading.Thread(target=self._restart_actors, args=(moving,),
                             kwargs={"migrate_from": addr}, daemon=True,
                             name="gcs-drain-migrate").start()
        return True

    def _op_node_drained(self, node_id: bytes):
        """The node (or the grace-window deadline) reports the drain
        finished: all queued/running work completed."""
        with self._lock:
            info = self._nodes.get(node_id)
            if info is not None:
                self._apply_drained_locked(info)
        return True

    def _op_list_nodes(self, alive_only: bool = False):
        with self._lock:
            return {
                "version": self._view_version,
                "nodes": [i.view() for i in self._nodes.values()
                          if not alive_only or i.state == "ALIVE"],
            }

    def _op_wait_nodes(self, count: int, timeout: float):
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                alive = [i for i in self._nodes.values() if i.state == "ALIVE"]
                if len(alive) >= count:
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)

    # -- drivers (owners)

    def _op_register_driver(self, driver_id: bytes, meta: dict = None):
        with self._lock:
            self._drivers[driver_id] = time.monotonic()
        return True

    def _op_driver_heartbeat(self, driver_id: bytes) -> bool:
        """False tells the driver to re-register (GCS restarted and lost
        the transient registry)."""
        with self._lock:
            if driver_id not in self._drivers:
                return False
            self._drivers[driver_id] = time.monotonic()
            return True

    def _op_unregister_driver(self, driver_id: bytes):
        """Clean driver exit: no death event — nodes keep its objects
        until normal eviction (a deliberate exit usually follows gets)."""
        with self._lock:
            self._drivers.pop(driver_id, None)
        return True

    def _op_driver_deaths_since(self, seq: int):
        with self._lock:
            return [d for d in self._driver_deaths if d[0] > seq]

    def _mark_driver_dead_locked(self, driver_id: bytes):
        self._drivers.pop(driver_id, None)
        self._driver_death_seq += 1
        self._driver_deaths.append((self._driver_death_seq, driver_id))
        if len(self._driver_deaths) > 256:
            del self._driver_deaths[:-256]
        # stop restarting the dead driver's NON-detached actors; detached
        # ones outlive their driver by definition. BUFFER the drops for
        # the WAL (self._lock is held — same discipline as node deaths):
        # without the record, a GCS restart would replay
        # register_actor_spec and resurrect an ownerless actor forever.
        for aid, spec in list(self._actor_specs.items()):
            opts = spec.get("opts") or {}
            if (spec.get("owner") == driver_id
                    and opts.get("lifetime") != "detached"):
                del self._actor_specs[aid]
                if self._wal is not None:
                    self._wal_pending.append(("drop_actor_spec", (aid,)))
        # persist the death (like node __death__ records): a restarted
        # GCS must keep the seq monotonic, or nodes whose watermark is
        # already past a reset-to-0 seq would never see new deaths
        if self._wal is not None:
            self._wal_pending.append(("__driver_death__", (driver_id,)))
        self._cond.notify_all()

    def _op_deaths_since(self, seq: int):
        with self._lock:
            return [(s, nid) for s, nid in self._deaths if s > seq]

    # -- eager-free tombstones

    def _op_freed_add(self, oid_bytes_list):
        from ray_tpu.core.runtime import note_freed

        with self._lock:
            note_freed(self._freed, oid_bytes_list, cap=1_000_000)
            # broadcast on the "freed" channel: every driver must
            # invalidate its lineage for these ids ("free means dead"),
            # not just discover the tombstone lazily at reconstruction
            # time — a dead entry would otherwise sit charged against
            # the lineage byte budget until evicted
            self._publish_locked("freed", list(oid_bytes_list))
        return True

    def _op_freed_check(self, oid_bytes: bytes) -> bool:
        with self._lock:
            return oid_bytes in self._freed

    # -- kv

    def _op_kv(self, op: str, key: str, value=None):
        with self._lock:
            if op == "put":
                self._kv[key] = value
                return True
            if op == "get":
                return self._kv.get(key)
            if op == "del":
                return self._kv.pop(key, None) is not None
            if op == "exists":
                return key in self._kv
            if op == "keys":
                return [k for k in self._kv if k.startswith(key)]
            if op == "merge":
                # atomic read-modify-write for dict values: concurrent
                # writers can't lose each other's fields
                cur = self._kv.setdefault(key, {})
                cur.update(value or {})
                return dict(cur)
            if op == "cas_merge":
                # value = (expect: {field: val}, updates: {field: val});
                # merge only if every expected field matches; returns the
                # merged dict or None on mismatch
                expect, updates = value
                cur = self._kv.get(key)
                if cur is None or any(cur.get(k) != v
                                      for k, v in expect.items()):
                    return None
                cur.update(updates)
                return dict(cur)
        raise ValueError(f"unknown kv op {op!r}")

    # -- named actors / actor table

    def _op_name_actor(self, name: str, actor_id: bytes, node_addr):
        with self._lock:
            if name in self._named_actors:
                existing_id, _ = self._named_actors[name]
                if existing_id != actor_id:
                    raise ValueError(f"actor name {name!r} already taken")
            self._named_actors[name] = (actor_id, tuple(node_addr))
        return True

    def _op_get_named_actor(self, name: str):
        with self._lock:
            return self._named_actors.get(name)

    def _op_drop_actor_name(self, name: str, actor_id: bytes):
        with self._lock:
            cur = self._named_actors.get(name)
            if cur is not None and cur[0] == actor_id:
                del self._named_actors[name]
        return True

    def _op_register_actor(self, actor_id: bytes, info: dict):
        with self._lock:
            self._actor_table.setdefault(actor_id, {}).update(info)
        return True

    def _op_register_actor_spec(self, actor_id: bytes, spec: dict):
        """Hand the GCS restart authority for this actor: spec carries
        {cls_fn_id, payload, deps, opts, name}; the class pickle must be
        in the GCS function table (register_fn) so a restart can ship it."""
        with self._lock:
            self._actor_specs[actor_id] = dict(spec)
        return True

    def _op_drop_actor_spec(self, actor_id: bytes):
        with self._lock:
            self._actor_specs.pop(actor_id, None)
        return True

    def _op_list_actors(self):
        with self._lock:
            return dict(self._actor_table)

    # -- object directory

    def _op_loc_add(self, oid: bytes, node_addr, nbytes: Optional[int] = None):
        with self._lock:
            locs = self._locations.setdefault(oid, [])
            addr = tuple(node_addr)
            if addr not in locs:
                locs.append(addr)
            if nbytes is not None:
                self._obj_sizes[oid] = int(nbytes)
            self._cond.notify_all()
        return True

    def _op_loc_add_batch(self, oids: List[bytes], node_addr,
                          sizes: Optional[List[Optional[int]]] = None):
        addr = tuple(node_addr)
        with self._lock:
            for i, oid in enumerate(oids):
                locs = self._locations.setdefault(oid, [])
                if addr not in locs:
                    locs.append(addr)
                if sizes is not None and sizes[i] is not None:
                    self._obj_sizes[oid] = int(sizes[i])
            self._cond.notify_all()
        return True

    def _op_loc_get(self, oid: bytes, timeout: float = 0.0):
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                locs = self._locations.get(oid)
                if locs:
                    return list(locs)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)

    def _op_loc_get_batch(self, oids: List[bytes]):
        """Resolve many ids in one RPC: {oid: (addrs, nbytes_or_None)}.

        Non-blocking by design (unlike loc_get's optional wait): callers
        use it to resolve a whole submission's deps for locality scoring,
        where "unknown yet" is an acceptable answer. Ids with no known
        location are omitted from the reply."""
        with self._lock:
            out = {}
            for oid in oids:
                locs = self._locations.get(oid)
                if locs:
                    out[oid] = (list(locs), self._obj_sizes.get(oid))
            return out

    def _op_loc_drop(self, oid: bytes, node_addr):
        addr = tuple(node_addr)
        with self._lock:
            locs = self._locations.get(oid)
            if locs and addr in locs:
                locs.remove(addr)
                if not locs:
                    del self._locations[oid]
                    self._obj_sizes.pop(oid, None)
        return True

    # -- pubsub

    _CHANNEL_CAP = 10_000

    def _publish_locked(self, channel: str, message):
        seq = self._channel_seq.get(channel, 0) + 1
        self._channel_seq[channel] = seq
        log = self._channels.setdefault(channel, [])
        log.append((seq, message))
        if len(log) > self._CHANNEL_CAP:
            del log[: len(log) - self._CHANNEL_CAP]
        self._cond.notify_all()

    def _op_publish(self, channel: str, message):
        with self._lock:
            self._publish_locked(channel, message)
            return self._channel_seq[channel]

    def _op_poll(self, channel: str, since_seq: int, timeout: float = 0.0):
        """Long-poll subscribe: messages with seq > since_seq, blocking up
        to ``timeout`` for the first one. Returns [(seq, message)].

        Seqs are contiguous per channel, so a slow subscriber can DETECT
        trimming: if the first returned seq > since_seq + 1, the log was
        truncated past its cursor and it should resync from a snapshot."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                if self._channel_seq.get(channel, 0) > since_seq:
                    log = self._channels[channel]
                    # contiguous seqs: index the tail instead of scanning
                    first_seq = log[0][0]
                    start = max(0, since_seq + 1 - first_seq)
                    return log[start:]
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)

    # -- function table

    def _op_register_fn(self, fn_id: bytes, pickled: bytes):
        with self._lock:
            self._functions.setdefault(fn_id, pickled)
        return True

    def _op_get_fn(self, fn_id: bytes):
        with self._lock:
            return self._functions.get(fn_id)

    # -- lifecycle

    def _op_ping(self):
        return "pong"

    def _op_netem(self, cmd: str, *args):
        """Remote control for the netem shim in THIS process: the test
        fixture arms/clears partition rules on the GCS side of an edge
        over a still-healthy path (see core/netem.py)."""
        return netem.control(cmd, *args)

    def _op_gcs_info(self):
        """Identity + recovery status + resync cursors, in one read.

        Clients reconnecting after an outage compare ``epoch`` to the one
        they last saw: a change means the head restarted, so they
        re-register and clamp their pubsub/death cursors to the returned
        heads (after an EMPTY restart the heads reset to 0 and a cursor
        left high would skip every future event; after a persisted
        restart the heads are >= the cursors and nothing moves)."""
        with self._lock:
            return {
                "epoch": self._epoch,
                "epoch_seq": self._epoch_seq,
                "fenced": self._fenced,
                "recovering": time.monotonic() < self._recovering_until,
                "view_version": self._view_version,
                "nodes_alive": sum(1 for i in self._nodes.values()
                                   if i.state == "ALIVE"),
                "channel_seq": dict(self._channel_seq),
                "death_seq": self._death_seq,
                "driver_death_seq": self._driver_death_seq,
            }

    def _op_shutdown_gcs(self):
        threading.Thread(target=self.close, daemon=True).start()
        return True

    def close(self):
        self._stop = True
        if self._wal is not None:
            with self._wal_lock:
                try:
                    self._compact_locked()
                # rtpu-lint: disable=L4 — shutdown-time compaction is an
                # optimization (disk full, unpicklable entry): the
                # uncompacted WAL replays fine on the next start
                except Exception:  # noqa: BLE001
                    pass
                self._wal.close()
                self._wal = None
        self._server.close()


def main(argv=None):
    import argparse
    import signal
    import sys

    p = argparse.ArgumentParser(description="ray_tpu GCS server")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--persist-dir", default=None,
                   help="directory for the WAL + snapshots; a restarted "
                        "GCS on the same dir rehydrates cluster state")
    args = p.parse_args(argv)
    gcs = GcsServer(port=args.port, persistence_path=args.persist_dir)
    # Parent reads the bound address from stdout.
    print(f"GCS_ADDRESS {gcs.address[0]}:{gcs.address[1]}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    gcs.close()
    sys.exit(0)


if __name__ == "__main__":
    main()
