"""GCS — the cluster control plane.

One process holding the authoritative cluster state, mirroring the
reference's gcs_server (src/ray/gcs/gcs_server/gcs_server.h:78) at the
capability level:

- node table + health: registration, periodic heartbeats with resource
  loads, a monitor thread that marks silent nodes DEAD and records a death
  event stream (reference: gcs_node_manager.h:45,
  gcs_health_check_manager.h:39)
- named actor directory (gcs_actor_manager)
- object location directory with blocking waits (the reference spreads this
  across the ownership layer + object directory; here the GCS is the
  rendezvous so any node can find any object's owner)
- cluster KV (gcs_kv_manager) and a cluster function table
  (function_manager.py exports to GCS in the reference)

Run as ``python -m ray_tpu.core.cluster.gcs --port N``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.cluster.rpc import RpcServer, cluster_authkey
from ray_tpu.core.config import config


class _NodeInfo:
    __slots__ = ("node_id", "address", "resources", "topology", "labels",
                 "state", "last_heartbeat", "avail", "load", "death_seq")

    def __init__(self, node_id: bytes, address, resources, topology, labels):
        self.node_id = node_id
        self.address = tuple(address)
        self.resources = dict(resources)       # total resources
        self.topology = topology               # TPU topology summary (dict)
        self.labels = dict(labels or {})
        self.state = "ALIVE"
        self.last_heartbeat = time.monotonic()
        self.avail = dict(resources)           # latest reported availability
        self.load = 0                          # queued+running tasks
        self.death_seq = None

    def view(self) -> dict:
        return {
            "node_id": self.node_id,
            "address": self.address,
            "resources": self.resources,
            "topology": self.topology,
            "labels": self.labels,
            "state": self.state,
            "avail": self.avail,
            "load": self.load,
        }


class GcsServer:
    """In-process GCS server (embed in a dedicated process via main())."""

    def __init__(self, port: int = 0, authkey: Optional[bytes] = None):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._nodes: Dict[bytes, _NodeInfo] = {}
        self._kv: Dict[str, Any] = {}
        self._named_actors: Dict[str, Tuple[bytes, tuple]] = {}
        self._actor_table: Dict[bytes, dict] = {}
        self._locations: Dict[bytes, List[tuple]] = {}
        self._functions: Dict[bytes, bytes] = {}
        self._deaths: List[Tuple[int, bytes]] = []  # (seq, node_id)
        self._death_seq = 0
        # pubsub channels: bounded event logs with long-poll subscribers
        # (reference: src/ray/pubsub/publisher.h:296)
        self._channels: Dict[str, List[Tuple[int, Any]]] = {}
        self._channel_seq: Dict[str, int] = {}
        # eager-free tombstones (worker-originated frees): bounded,
        # insertion-ordered — consulted before any fetch-retry spin or
        # lineage reconstruction so "free means dead" holds cluster-wide
        self._freed: Dict[bytes, None] = {}
        self._view_version = 0
        self._stop = False
        self._server = RpcServer(self._handle, authkey or cluster_authkey(),
                                 port=port)
        self.address = self._server.address
        self._monitor = threading.Thread(target=self._health_loop,
                                         daemon=True, name="gcs-health")
        self._monitor.start()

    # ------------------------------------------------------------ health

    def _health_loop(self):
        timeout = config.gcs_heartbeat_timeout_s
        while not self._stop:
            time.sleep(min(0.1, timeout / 4))
            now = time.monotonic()
            with self._lock:
                for info in self._nodes.values():
                    if (info.state == "ALIVE"
                            and now - info.last_heartbeat > timeout):
                        self._mark_dead_locked(info)

    def _mark_dead_locked(self, info: _NodeInfo):
        info.state = "DEAD"
        self._death_seq += 1
        info.death_seq = self._death_seq
        self._deaths.append((self._death_seq, info.node_id))
        self._publish_locked("node_deaths", {
            "node_id": info.node_id, "address": list(info.address)})
        self._view_version += 1
        # objects whose only location was the dead node are now lost
        dead_addr = info.address
        for oid, locs in list(self._locations.items()):
            locs = [a for a in locs if a != dead_addr]
            if locs:
                self._locations[oid] = locs
            else:
                del self._locations[oid]
        self._cond.notify_all()

    # ------------------------------------------------------------ handler

    def _handle(self, msg, ctx) -> Any:
        op = msg[0]
        fn = getattr(self, "_op_" + op, None)
        if fn is None:
            raise ValueError(f"unknown GCS op {op!r}")
        return fn(*msg[1:])

    # -- nodes

    def _op_register_node(self, node_id: bytes, address, resources,
                          topology, labels=None):
        with self._lock:
            self._nodes[node_id] = _NodeInfo(node_id, address, resources,
                                             topology, labels)
            self._view_version += 1
            self._cond.notify_all()
        return True

    def _op_heartbeat(self, node_id: bytes, avail: dict, load: int):
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None or info.state == "DEAD":
                return {"accepted": False}  # node must re-register
            info.last_heartbeat = time.monotonic()
            if info.avail != avail or info.load != load:
                info.avail = dict(avail)
                info.load = load
                self._view_version += 1
        return {"accepted": True}

    def _op_unregister_node(self, node_id: bytes):
        with self._lock:
            info = self._nodes.get(node_id)
            if info is not None and info.state == "ALIVE":
                self._mark_dead_locked(info)
        return True

    def _op_list_nodes(self, alive_only: bool = False):
        with self._lock:
            return {
                "version": self._view_version,
                "nodes": [i.view() for i in self._nodes.values()
                          if not alive_only or i.state == "ALIVE"],
            }

    def _op_wait_nodes(self, count: int, timeout: float):
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                alive = [i for i in self._nodes.values() if i.state == "ALIVE"]
                if len(alive) >= count:
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)

    def _op_deaths_since(self, seq: int):
        with self._lock:
            return [(s, nid) for s, nid in self._deaths if s > seq]

    # -- eager-free tombstones

    def _op_freed_add(self, oid_bytes_list):
        from ray_tpu.core.runtime import note_freed

        with self._lock:
            note_freed(self._freed, oid_bytes_list, cap=1_000_000)
        return True

    def _op_freed_check(self, oid_bytes: bytes) -> bool:
        with self._lock:
            return oid_bytes in self._freed

    # -- kv

    def _op_kv(self, op: str, key: str, value=None):
        with self._lock:
            if op == "put":
                self._kv[key] = value
                return True
            if op == "get":
                return self._kv.get(key)
            if op == "del":
                return self._kv.pop(key, None) is not None
            if op == "exists":
                return key in self._kv
            if op == "keys":
                return [k for k in self._kv if k.startswith(key)]
            if op == "merge":
                # atomic read-modify-write for dict values: concurrent
                # writers can't lose each other's fields
                cur = self._kv.setdefault(key, {})
                cur.update(value or {})
                return dict(cur)
            if op == "cas_merge":
                # value = (expect: {field: val}, updates: {field: val});
                # merge only if every expected field matches; returns the
                # merged dict or None on mismatch
                expect, updates = value
                cur = self._kv.get(key)
                if cur is None or any(cur.get(k) != v
                                      for k, v in expect.items()):
                    return None
                cur.update(updates)
                return dict(cur)
        raise ValueError(f"unknown kv op {op!r}")

    # -- named actors / actor table

    def _op_name_actor(self, name: str, actor_id: bytes, node_addr):
        with self._lock:
            if name in self._named_actors:
                existing_id, _ = self._named_actors[name]
                if existing_id != actor_id:
                    raise ValueError(f"actor name {name!r} already taken")
            self._named_actors[name] = (actor_id, tuple(node_addr))
        return True

    def _op_get_named_actor(self, name: str):
        with self._lock:
            return self._named_actors.get(name)

    def _op_drop_actor_name(self, name: str, actor_id: bytes):
        with self._lock:
            cur = self._named_actors.get(name)
            if cur is not None and cur[0] == actor_id:
                del self._named_actors[name]
        return True

    def _op_register_actor(self, actor_id: bytes, info: dict):
        with self._lock:
            self._actor_table.setdefault(actor_id, {}).update(info)
        return True

    def _op_list_actors(self):
        with self._lock:
            return dict(self._actor_table)

    # -- object directory

    def _op_loc_add(self, oid: bytes, node_addr):
        with self._lock:
            locs = self._locations.setdefault(oid, [])
            addr = tuple(node_addr)
            if addr not in locs:
                locs.append(addr)
            self._cond.notify_all()
        return True

    def _op_loc_add_batch(self, oids: List[bytes], node_addr):
        addr = tuple(node_addr)
        with self._lock:
            for oid in oids:
                locs = self._locations.setdefault(oid, [])
                if addr not in locs:
                    locs.append(addr)
            self._cond.notify_all()
        return True

    def _op_loc_get(self, oid: bytes, timeout: float = 0.0):
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                locs = self._locations.get(oid)
                if locs:
                    return list(locs)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)

    def _op_loc_drop(self, oid: bytes, node_addr):
        addr = tuple(node_addr)
        with self._lock:
            locs = self._locations.get(oid)
            if locs and addr in locs:
                locs.remove(addr)
                if not locs:
                    del self._locations[oid]
        return True

    # -- pubsub

    _CHANNEL_CAP = 10_000

    def _publish_locked(self, channel: str, message):
        seq = self._channel_seq.get(channel, 0) + 1
        self._channel_seq[channel] = seq
        log = self._channels.setdefault(channel, [])
        log.append((seq, message))
        if len(log) > self._CHANNEL_CAP:
            del log[: len(log) - self._CHANNEL_CAP]
        self._cond.notify_all()

    def _op_publish(self, channel: str, message):
        with self._lock:
            self._publish_locked(channel, message)
            return self._channel_seq[channel]

    def _op_poll(self, channel: str, since_seq: int, timeout: float = 0.0):
        """Long-poll subscribe: messages with seq > since_seq, blocking up
        to ``timeout`` for the first one. Returns [(seq, message)].

        Seqs are contiguous per channel, so a slow subscriber can DETECT
        trimming: if the first returned seq > since_seq + 1, the log was
        truncated past its cursor and it should resync from a snapshot."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                if self._channel_seq.get(channel, 0) > since_seq:
                    log = self._channels[channel]
                    # contiguous seqs: index the tail instead of scanning
                    first_seq = log[0][0]
                    start = max(0, since_seq + 1 - first_seq)
                    return log[start:]
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)

    # -- function table

    def _op_register_fn(self, fn_id: bytes, pickled: bytes):
        with self._lock:
            self._functions.setdefault(fn_id, pickled)
        return True

    def _op_get_fn(self, fn_id: bytes):
        with self._lock:
            return self._functions.get(fn_id)

    # -- lifecycle

    def _op_ping(self):
        return "pong"

    def _op_shutdown_gcs(self):
        threading.Thread(target=self.close, daemon=True).start()
        return True

    def close(self):
        self._stop = True
        self._server.close()


def main(argv=None):
    import argparse
    import signal
    import sys

    p = argparse.ArgumentParser(description="ray_tpu GCS server")
    p.add_argument("--port", type=int, default=0)
    args = p.parse_args(argv)
    gcs = GcsServer(port=args.port)
    # Parent reads the bound address from stdout.
    print(f"GCS_ADDRESS {gcs.address[0]}:{gcs.address[1]}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    gcs.close()
    sys.exit(0)


if __name__ == "__main__":
    main()
