"""Pull admission control for cross-node object fetches.

Reference: src/ray/object_manager/pull_manager.h:52 — the reference caps
in-flight pulls by available object-store memory and services requests in
priority order (task arguments first, then explicit ray.get, then
ray.wait). Same policy here: each fetch reserves its payload size before
transferring; the budget derives from the store's capacity, so a wide
fetch fan-in queues instead of over-committing store + heap.

A pull larger than the whole budget is admitted only when nothing else is
in flight (a single oversized object must still make progress — the
reference relaxes its cap the same way)."""

from __future__ import annotations

import heapq
import threading
import time
from typing import Optional

from ray_tpu.util.debug_lock import make_lock

PRIO_TASK_ARGS = 0
PRIO_GET = 1
PRIO_WAIT = 2

_PRIO_NAMES = {PRIO_TASK_ARGS: "task_args", PRIO_GET: "get",
               PRIO_WAIT: "wait"}


def prio_name(p: int) -> str:
    return _PRIO_NAMES.get(p, str(p))


class PullManager:
    """Byte-budgeted, priority-ordered admission for object pulls."""

    def __init__(self, budget_bytes: int):
        self.budget_bytes = max(1, budget_bytes)
        self._inflight = 0
        self._seq = 0
        self._waiting = []  # heap of (priority, seq); head = next admitted
        self._granted = set()
        self._cv = threading.Condition(make_lock("PullManager._cv"))

    def acquire(self, nbytes: int, priority=PRIO_GET,
                timeout: Optional[float] = None) -> bool:
        """Block until ``nbytes`` of transfer budget is granted (False on
        timeout). Strict priority: only the best-priority waiter is
        admitted next, so task-argument pulls overtake queued get/wait
        pulls during pressure.

        ``priority`` may be a 1-element mutable list ("priority box"): a
        concurrent upgrade (ensure_available from a more urgent
        requester) takes effect at the next wakeup WITHOUT losing the
        waiter's queue position — its original seq is kept, so smaller
        same-priority pulls can never leapfrog it (an oversized pull at
        the head eventually sees inflight==0 and is admitted)."""
        box = priority if isinstance(priority, list) else [priority]
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            me = [box[0], self._seq]  # mutable: priority may upgrade
            self._seq += 1
            heapq.heappush(self._waiting, me)
            try:
                while True:
                    if box[0] != me[0]:
                        # re-rank under the upgraded priority, SAME seq
                        me[0] = box[0]
                        heapq.heapify(self._waiting)
                    if self._waiting[0] is me and (
                            self._inflight == 0
                            or self._inflight + nbytes
                            <= self.budget_bytes):
                        self._inflight += nbytes
                        return True
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        return False
                    # bounded wait: a priority-box upgrade has no
                    # notifier, so re-check it at least once a second
                    self._cv.wait(1.0 if remaining is None
                                  else min(remaining, 1.0))
            finally:
                # success or timeout: leave the queue either way
                self._waiting.remove(me)
                heapq.heapify(self._waiting)
                self._cv.notify_all()

    def release(self, nbytes: int):
        with self._cv:
            self._inflight -= nbytes
            self._cv.notify_all()

    def stats(self) -> dict:
        with self._cv:
            return {"inflight_bytes": self._inflight,
                    "budget_bytes": self.budget_bytes,
                    "queued": len(self._waiting)}
