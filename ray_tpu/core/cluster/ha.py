"""Head-node availability: GCS failover with cluster-wide ride-through.

The GCS already persists every state-mutating op to a snapshot+WAL
(gcs.py), so a restarted head rehydrates nodes/actors/locations/KV/
pubsub seqs by itself. This module supplies the *client* half of
failover — what drivers and node servers do while the head is down and
right after it comes back:

- ``HaGcsClient`` wraps the transport ``RpcClient`` with a bounded
  ride-through buffer: calls that fail because the head is unreachable
  park and retry (with backoff+jitter) until ``gcs_reconnect_timeout_s``
  elapses or ``gcs_op_buffer_max`` calls are already parked, then fail
  with the typed ``GcsUnavailableError`` — the cluster-level mirror of
  ``ActorUnavailableError``'s bounded-buffering semantics. Only ops
  ``WIRE_CONTRACT`` (protocol_meta.py — the single source of truth for
  wire retry classes) marks retry-safe are ever replayed once their
  request may have been applied (lost reply), so at-least-once delivery
  stays indistinguishable from exactly-once.
- Epoch tracking: every GCS process mints a fresh ``epoch``
  (never persisted) and stamps it on heartbeat replies and
  ``gcs_info``. A changed epoch means the head restarted — even a fast
  restart between two heartbeats that never failed a call — and
  triggers ``resync_node`` / the driver's reconnect hook.
- ``resync_node`` re-pushes one node's slice of cluster state into a
  (possibly empty) restarted GCS: re-register under the SAME node_id,
  re-publish every sealed object location with sizes, re-register live
  actor incarnations (re-claiming names), re-publish placement-group
  state, and clamp the driver-death cursor so an empty head's reset
  seqs don't strand the watermark.

Reference: the GcsServer + Redis-backed fault tolerance split
(src/ray/gcs/gcs_server/gcs_server.h:78, gcs_rpc_client.h retryable
method table); here the WAL replaces Redis and this module replaces the
raylet/core-worker reconnect machinery.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Optional, Tuple

from ray_tpu.core import netem
from ray_tpu.core.cluster.rpc import RpcClient, RpcError
from ray_tpu.core.config import config
from ray_tpu.exceptions import GcsUnavailableError
from ray_tpu.util.debug_lock import check_fire_outside, make_lock

# Per-attempt connect budget inside the ride-through loop: short, so the
# loop (not the transport) owns pacing against gcs_reconnect_timeout_s.
_ATTEMPT_TIMEOUT_S = 2.0


class HaGcsClient:
    """GCS client with head-outage ride-through.

    Drop-in for ``RpcClient`` where the peer is the GCS (same ``call`` /
    ``try_call`` / ``close`` / ``address`` surface). ``call`` buffers
    across an outage within the configured bounds; ``try_call`` stays
    strictly best-effort (heartbeats and batched location flushes must
    not park threads for the whole reconnect window). ``on_reconnect``
    — when given — fires once per detected GCS restart (epoch change)
    with the fresh ``gcs_info`` dict, from the thread that noticed.
    """

    def __init__(self, address: Tuple[str, int], authkey: bytes,
                 on_reconnect: Optional[Callable[[dict], None]] = None):
        self.address = tuple(address)
        netem.tag_peer(self.address, "gcs")  # role-selector rules match it
        self._rpc = RpcClient(self.address, authkey,
                              connect_timeout=_ATTEMPT_TIMEOUT_S,
                              unavailable_exc=GcsUnavailableError)
        self._on_reconnect = on_reconnect
        self._lock = make_lock("HaGcsClient._lock")
        self._buffered = 0          # calls currently parked in ride-through
        self._epoch: Optional[str] = None   # last GCS incarnation seen
        self._saw_outage = False    # a call failed since the last epoch check
        self._closed = False

    # ------------------------------------------------------------- calls

    def call(self, msg: Any) -> Any:
        r0 = self._rpc.reconnects
        try:
            result = self._rpc.call(msg)
        except RpcError as e:
            return self._ride_through(msg, e)
        if self._epoch_suspect(r0):
            self._check_epoch()
        return result

    def try_call(self, msg: Any, default=None):
        """Best-effort call: no ride-through buffering, still epoch-aware
        (a success right after an outage triggers the reconnect hook)."""
        r0 = self._rpc.reconnects
        try:
            result = self._rpc.call(msg)
        except RpcError:
            with self._lock:
                self._saw_outage = True
            return default
        if self._epoch_suspect(r0):
            self._check_epoch()
        return result

    def _epoch_suspect(self, r0: int) -> bool:
        """True when the GCS incarnation needs re-verifying: never seen
        an epoch, a call failed since the last check, or the transport
        silently re-dialed mid-call (fast head restart that never
        surfaced an error — the peer may be a different incarnation)."""
        with self._lock:
            return self._epoch is None or self._saw_outage \
                or self._rpc.reconnects != r0

    def _ride_through(self, msg: Any, first_err: RpcError) -> Any:
        op = msg[0] if isinstance(msg, tuple) and msg else msg
        if getattr(first_err, "maybe_applied", False):
            # the request reached the head and the op is NOT on the
            # retry-after-apply whitelist: blind replay could run the
            # side effect twice — surface instead of buffering
            raise GcsUnavailableError(
                f"GCS call {op!r} may already have been applied (reply "
                f"lost) and is not replay-safe") from first_err
        with self._lock:
            if self._closed:
                raise first_err
            if self._buffered >= config.gcs_op_buffer_max:
                raise GcsUnavailableError(
                    f"GCS at {self.address} is unreachable and "
                    f"{self._buffered} calls are already parked "
                    f"(gcs_op_buffer_max={config.gcs_op_buffer_max})"
                ) from first_err
            self._buffered += 1
            self._saw_outage = True
        try:
            deadline = time.monotonic() + config.gcs_reconnect_timeout_s
            delay = 0.05
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise GcsUnavailableError(
                        f"GCS at {self.address} unreachable past the "
                        f"ride-through window (gcs_reconnect_timeout_s="
                        f"{config.gcs_reconnect_timeout_s:g}); last "
                        f"error: {first_err}") from first_err
                # backoff with full jitter: a restarted head sees every
                # buffered call in the cluster wake at once
                time.sleep(min(delay * (0.5 + random.random()), remaining))
                delay = min(delay * 2, 1.0)
                with self._lock:
                    if self._closed:
                        raise first_err
                try:
                    result = self._rpc.call(msg)
                except RpcError as e:
                    if getattr(e, "maybe_applied", False):
                        raise GcsUnavailableError(
                            f"GCS call {op!r} may already have been "
                            f"applied (reply lost) and is not replay-"
                            f"safe") from e
                    first_err = e
                    continue
                self._check_epoch()
                return result
        finally:
            with self._lock:
                self._buffered -= 1

    # ------------------------------------------------------------- epoch

    def _check_epoch(self):
        """Refresh the known GCS incarnation; fire ``on_reconnect`` when
        it changed (i.e. the head restarted since we last looked)."""
        try:
            info = self._rpc.call(("gcs_info",))
        except RpcError:
            return
        if not isinstance(info, dict) or "epoch" not in info:
            return
        with self._lock:
            prev, self._epoch = self._epoch, info["epoch"]
            self._saw_outage = False
        if prev is not None and prev != info["epoch"] \
                and self._on_reconnect is not None:
            # resync code re-enters the GCS client; firing it under
            # _lock would deadlock the ride-through bookkeeping
            check_fire_outside("HaGcsClient._check_epoch.on_reconnect")
            try:
                self._on_reconnect(info)
            # rtpu-lint: disable=L4 — the reconnect hook is arbitrary
            # resync code; a bug there must not poison the call that
            # merely detected the restart (the result is still good)
            except Exception:  # noqa: BLE001
                pass

    @property
    def epoch(self) -> Optional[str]:
        with self._lock:
            return self._epoch

    @property
    def buffered(self) -> int:
        """Calls currently parked in the ride-through buffer."""
        with self._lock:
            return self._buffered

    def close(self):
        # parked ride-through loops notice _closed at their next wakeup
        # and fail with the original transport error
        with self._lock:
            self._closed = True
        self._rpc.close()


# ---------------------------------------------------------------- resync


def resync_node(server) -> bool:
    """Push one node's slice of cluster state back into the GCS.

    Runs after a detected head restart (epoch change or rejected
    heartbeat): the restarted GCS may have rehydrated from snapshot+WAL
    (then everything here is an idempotent no-op — all ops are on the
    retry-after-apply whitelist) or come back EMPTY (then this rebuilds
    its node/directory/actor/PG rows). Re-registering under the same
    node_id replaces the GCS row wholesale, so resources are never
    double-counted. Returns False when the head went away again
    mid-resync; the caller retries on the next epoch mismatch.
    """
    from ray_tpu.core.cluster.node_server import payload_nbytes

    rt = server.runtime
    try:
        server.gcs.call(server.register_msg())

        # replay the freed channel BEFORE re-publishing locations: frees
        # broadcast while this node was partitioned must land first, or
        # the batch below re-advertises a stale copy of a freed object
        # (and a getter could read it back). An EMPTY restart reset the
        # channel seq, so clamp the cursor to the head's watermark first.
        info = server.gcs.call(("gcs_info",))
        if isinstance(info, dict):
            server._clamp_freed_cursor(
                info.get("channel_seq", {}).get("freed", 0))
        server._drain_freed()

        # sealed object locations, with sizes for the locality scorer;
        # collect under the runtime lock, measure + publish outside it
        with rt._lock:
            sealed = [(oid, e.payload) for oid, e in rt._objects.items()
                      if e.event.is_set() and e.payload is not None
                      and oid.binary() not in rt._freed]
        batch = []
        for oid, payload in sealed:
            b = oid.binary()
            if b in server._unpublished:
                continue
            batch.append((b, payload_nbytes(rt, payload)))
        for i in range(0, len(batch), 1000):
            chunk = batch[i:i + 1000]
            server.gcs.call(("loc_add_batch", [b for b, _ in chunk],
                             server.address, [n for _, n in chunk]))

        # live actor incarnations; re-claim names we rightfully hold
        with rt._lock:
            actors = [(aid, st.name, st.incarnation)
                      for aid, st in rt._actors.items() if not st.dead]
        for aid, name, incarnation in actors:
            server.gcs.call(("register_actor", aid.binary(),
                             {"node": server.address, "state": "ALIVE",
                              "incarnation": incarnation, "name": name}))
            if name:
                try:
                    server.gcs.call(("name_actor", name, aid.binary(),
                                     server.address))
                except ValueError:
                    # another holder re-claimed it first: the directory
                    # (not this node) arbitrates duplicate names
                    pass

        # placement-group state, published into cluster KV so a fresh
        # head (and debugging humans) can see which bundles live here
        table = rt.placement_group_table()
        if table:
            server.gcs.call(("kv", "put",
                             "node_pgs:" + server.node_id.binary().hex(),
                             table))

        # clamp the driver-death watermark: an EMPTY restart reset the
        # seq to 0, and a cursor left high would skip every future death
        # (reuses the gcs_info snapshot fetched before the replay above)
        if isinstance(info, dict):
            server._driver_death_seq = min(
                server._driver_death_seq, info.get("driver_death_seq", 0))
    except RpcError:
        return False
    return True
