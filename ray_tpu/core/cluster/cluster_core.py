"""Driver-side core client for a multi-node cluster.

Implements the same interface the embedded single-node ``Runtime`` exposes
to the public API (api.py / actor.py / remote_function.py /
placement_group.py), but routes every operation to node servers over RPC:

- tasks: resource-fit node selection from the GCS cluster view (least
  loaded, most available), lazy per-node function shipping
- objects: owner-hint routed gets (the node a task was sent to serves its
  returns, proxying if it spilled the task), put to the home node
- actors: placement like tasks, location-transparent handles, restart on a
  different node when the hosting node dies (driver-side FSM; the
  reference's gcs_actor_manager does this inside the GCS)
- placement groups: cluster PGs composed of node-local PGs (STRICT_PACK
  pins one node; SPREAD distributes bundles round-robin)

The reference analogue of this layer is the CoreWorker's
NormalTaskSubmitter + ActorTaskSubmitter + ownership tables
(src/ray/core_worker/core_worker.h), minus distributed refcounting: the
driver owns every ref it creates, like the single-node runtime.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core import netem, protocol, serialization
from ray_tpu.core.cluster.ha import HaGcsClient
from ray_tpu.core.cluster.rpc import ClientCache, RpcError, cluster_authkey
from ray_tpu.core.config import config
from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.placement_group import PlacementGroup
from ray_tpu.util.debug_lock import make_lock
from ray_tpu.exceptions import (ActorDiedError, ActorUnavailableError,
                                GetTimeoutError, ObjectLostError,
                                ObjectTimeoutError, PlacementGroupError)


class _ClusterPG:
    __slots__ = ("pg_id", "bundles", "strategy", "name", "placements",
                 "node_pgs")

    def __init__(self, pg_id, bundles, strategy, name):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        # per-bundle: (node_addr, local_pg_id_bytes, local_bundle_index)
        self.placements: List[Tuple[Tuple[str, int], bytes, int]] = []
        # node_addr -> local_pg_id_bytes
        self.node_pgs: Dict[Tuple[str, int], bytes] = {}


class ClusterCore:
    """Driver client to a ray_tpu cluster (GCS + node servers)."""

    def __init__(self, gcs_address: Tuple[str, int],
                 authkey: Optional[bytes] = None):
        self._authkey = authkey or cluster_authkey()
        # netem source selector: outbound driver edges match "driver"
        # role rules (nothing dials the driver, so no listen address)
        netem.set_identity("driver")
        # ride-through GCS client: calls park (bounded by
        # gcs_op_buffer_max / gcs_reconnect_timeout_s) while the head is
        # down, then fail with the typed GcsUnavailableError; a detected
        # head restart re-registers this driver and clamps pubsub cursors
        self.gcs = HaGcsClient(tuple(gcs_address), self._authkey,
                               on_reconnect=self._on_gcs_reconnect)
        self.gcs.call(("ping",))
        self._nodes = ClientCache(self._authkey)
        self.job_id = JobID.from_random()
        self.node_id = NodeID.from_random()     # driver pseudo-node id
        self.worker_id = WorkerID.from_random()

        self._lock = make_lock("ClusterCore._lock")
        self._functions: Dict[bytes, bytes] = {}
        self._fn_cache: Dict[int, Tuple[bytes, Any]] = {}
        self._shipped: Dict[Tuple[str, int], set] = {}
        self._ref_node: Dict[bytes, Tuple[str, int]] = {}
        # actors whose restart FSM the GCS accepted (register_actor_spec
        # succeeded); the driver restarts only the others
        self._gcs_owned: set = set()
        # driver-side tombstones for eagerly freed ids: a get after free
        # must fail fast with the documented freed message, not spend the
        # fetch deadline discovering no copy exists (mirrors Runtime._freed;
        # insertion-ordered so note_freed evicts oldest-first)
        self._freed: Dict[bytes, None] = {}
        # lineage: first-return-id -> resubmittable task description, for
        # reconstructing objects lost to node death (reference:
        # object_recovery_manager.h:41). Keyed per return id.
        # insertion-ordered; evicted oldest-first under the byte budget
        from collections import OrderedDict
        self._lineage: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._lineage_bytes = 0
        self._reconstructions: Dict[bytes, int] = {}
        self._actor_node: Dict[ActorID, Tuple[str, int]] = {}
        self._actor_opts: Dict[ActorID, dict] = {}
        self._actor_spec: Dict[ActorID, tuple] = {}  # for restart
        self._pgs: Dict[PlacementGroupID, _ClusterPG] = {}
        # driver-local sentinel objects (e.g. cluster PG ready refs)
        self._local: Dict[bytes, Tuple[threading.Event, list]] = {}
        self._rr = 0
        # object-location cache: oid -> (addrs, cached_at). Fed by
        # loc_get_batch; invalidated by the GCS "freed" channel, node
        # death, and locality_cache_ttl_s. Only a scheduling hint —
        # staleness costs placement quality, never correctness.
        self._loc_cache: Dict[bytes, Tuple[List[Tuple[str, int]], float]] = {}
        # known object sizes (driver puts + directory replies); sizes are
        # immutable so entries never go stale, only die on free
        self._obj_size: Dict[bytes, int] = {}
        # locality-scheduling observability (mutated under self._lock):
        # hits/misses count submissions that did/didn't land on the node
        # holding the most qualifying argument bytes; bytes_local is the
        # cross-node transfer volume locality avoided, bytes_remote what
        # still has to move
        self.locality_stats: Dict[str, int] = {
            "hits": 0, "misses": 0, "bytes_local": 0, "bytes_remote": 0,
            "batched_lookups": 0, "cache_hits": 0,
        }

        self._view: Optional[dict] = None
        self._view_time = 0.0
        self._death_seq = 0
        self._freed_seq = 0  # cursor into the GCS "freed" channel
        # cursor into the GCS "actor_state" channel + last seen restart-FSM
        # state per actor (aid bytes -> message dict): RESTARTING gates
        # call retries on the restart finishing instead of failing fast
        self._actor_state_seq = 0
        self._actor_states: Dict[bytes, dict] = {}
        self._monitor_stop = False
        # owner identity: this driver registers with the GCS and
        # heartbeats; if it dies, nodes reclaim its objects and its
        # non-detached actors stop restarting (reference: owner-failure
        # semantics of reference_count.h:61, GCS-mediated)
        self._driver_id = self.job_id.binary()
        try:
            self.gcs.call(("register_driver", self._driver_id, {}))
        except RpcError:
            pass
        self._monitor = threading.Thread(target=self._death_watch,
                                         daemon=True, name="driver-deaths")
        self._monitor.start()

        view = self._cluster_view(force=True)
        if not view["nodes"]:
            raise RuntimeError("cluster has no alive nodes")
        self._home: Tuple[str, int] = tuple(view["nodes"][0]["address"])

        # local store fast path: if the home node is on this host, read big
        # objects straight out of its shm store (zero-copy) instead of TCP.
        self._home_store = None
        self.store = None
        try:
            import socket as _s

            home = next(n for n in view["nodes"]
                        if tuple(n["address"]) == self._home)
            if home["topology"].get("hostname") == _s.gethostname():
                from ray_tpu.core.object_store.store import ShmObjectStore

                self._home_store = ShmObjectStore.connect(
                    home["topology"]["store"])
                self.store = self._home_store
        except Exception:  # noqa: BLE001 — fast path is optional
            self._home_store = None

    # ------------------------------------------------------------- topology

    @property
    def topology(self):
        from ray_tpu.core.resources import TpuSliceTopology

        return TpuSliceTopology.detect()

    def _cluster_view(self, force: bool = False) -> dict:
        now = time.monotonic()
        if (not force and self._view is not None
                and now - self._view_time < config.cluster_view_refresh_s):
            return self._view
        view = self.gcs.call(("list_nodes", True))
        self._view = view
        self._view_time = now
        return view

    def _on_gcs_reconnect(self, info: dict):
        """The head restarted (epoch change): re-assert this driver's
        registration and clamp channel/death cursors to the fresh heads.
        After an EMPTY restart every seq restarts from 0, so a cursor
        left at its old (higher) value would silently skip every future
        freed/actor_state/death event; after a persisted restart the
        heads are >= the cursors and the clamps are no-ops."""
        try:
            self.gcs.try_call(("register_driver", self._driver_id, {}))
            heads = info.get("channel_seq") or {}
            with self._lock:
                self._freed_seq = min(self._freed_seq,
                                      heads.get("freed", 0))
                self._actor_state_seq = min(self._actor_state_seq,
                                            heads.get("actor_state", 0))
            self._death_seq = min(self._death_seq,
                                  info.get("death_seq", 0))
        # rtpu-lint: disable=L4 — reconnect hook runs inside whichever
        # call detected the restart; a malformed info dict must not
        # poison that call (the next heartbeat tick re-registers anyway)
        except Exception:  # noqa: BLE001
            pass

    def _death_watch(self):
        last_hb = 0.0
        # cadence must satisfy BOTH duties: node-death polling and the
        # driver heartbeat (whose timeout is independent of the node
        # heartbeat knobs — never let one flag starve the other)
        period = min(config.gcs_heartbeat_interval_s * 2,
                     config.driver_heartbeat_interval_s)
        while not self._monitor_stop:
            time.sleep(period)
            now = time.monotonic()
            if now - last_hb >= config.driver_heartbeat_interval_s:
                last_hb = now
                try:
                    if not self.gcs.call(
                            ("driver_heartbeat", self._driver_id)):
                        # GCS restarted and lost the (transient) driver
                        # registry: re-register and clamp cursors — an
                        # EMPTY restart also reset every pubsub seq
                        info = self.gcs.call(("gcs_info",))
                        self._on_gcs_reconnect(
                            info if isinstance(info, dict) else {})
                # rtpu-lint: disable=L4 — crash-proof daemon loop: call()
                # re-raises arbitrary picklable remote exceptions, and a
                # missed heartbeat during a GCS restart must not kill the
                # death watch (the next tick retries)
                except Exception:  # noqa: BLE001
                    pass
            try:
                deaths = self.gcs.call(("deaths_since", self._death_seq))
            # rtpu-lint: disable=L4 — same: any poll failure (GCS down,
            # mid-restart, remote error) just means try again next tick
            except Exception:  # noqa: BLE001
                continue
            self._drain_freed_channel()
            self._drain_actor_state_channel()
            for seq, node_id in deaths:
                self._death_seq = max(self._death_seq, seq)
                self._on_node_death(node_id)

    def _drain_freed_channel(self):
        """Apply freed-id broadcasts: a worker-originated free on any
        node must invalidate THIS driver's lineage for those ids ("free
        means dead" — reconstruction must never resurrect them, and the
        dead entries must stop counting against the lineage budget).
        freed_check at reconstruction time remains the authority; this
        is the eager path."""
        with self._lock:
            since = self._freed_seq
        try:
            msgs = self.gcs.call(("poll", "freed", since, 0.0))
        except (RpcError, OSError):
            return
        if not msgs:
            return
        from ray_tpu.core.runtime import note_freed

        with self._lock:
            for seq, oid_list in msgs:
                self._freed_seq = max(self._freed_seq, seq)
                note_freed(self._freed, oid_list)
                for b in oid_list:
                    self._drop_lineage_locked(b)
                    self._loc_cache.pop(b, None)
                    self._obj_size.pop(b, None)

    def _drain_actor_state_channel(self):
        """Apply actor-restart FSM broadcasts (the GCS ``actor_state``
        channel): ALIVE updates routing so the next call goes straight to
        the new incarnation's node; RESTARTING is remembered so call
        retries wait out the restart window instead of failing fast;
        DEAD is terminal (buffable-and-wait would hang forever)."""
        with self._lock:
            since = self._actor_state_seq
        try:
            msgs = self.gcs.call(("poll", "actor_state", since, 0.0))
        except (RpcError, OSError):
            return
        if not msgs:
            return
        with self._lock:
            for seq, m in msgs:
                self._actor_state_seq = max(self._actor_state_seq, seq)
                aid_b = m.get("actor_id")
                if aid_b is None:
                    continue
                self._actor_states[aid_b] = m
                aid = ActorID(aid_b)
                if m.get("state") == "ALIVE" and m.get("node"):
                    self._actor_node[aid] = tuple(m["node"])
                elif m.get("state") in ("RESTARTING", "DEAD"):
                    # stale routing either way: re-resolve on next call
                    self._actor_node.pop(aid, None)

    def _await_actor_restart(self, actor_id: ActorID) -> bool:
        """If the actor is mid-restart per the ``actor_state`` channel,
        block (bounded by ``actor_restart_timeout_s``) until the FSM
        publishes a terminal transition. Returns True when the actor came
        back ALIVE, False when no restart is known to be underway; raises
        when the restart failed or overran its window."""
        aid_b = actor_id.binary()
        state = (self._actor_states.get(aid_b) or {}).get("state")
        if state != "RESTARTING":
            return state == "ALIVE"
        deadline = time.monotonic() + config.actor_restart_timeout_s
        while time.monotonic() < deadline:
            self._drain_actor_state_channel()
            state = (self._actor_states.get(aid_b) or {}).get("state")
            if state == "ALIVE":
                return True
            if state == "DEAD":
                raise ActorDiedError(
                    f"actor {actor_id} died during restart",
                    cause="restart failed (budget exhausted or no node)")
            time.sleep(0.05)
        raise ActorUnavailableError(
            f"actor {actor_id} did not finish restarting within "
            f"actor_restart_timeout_s ({config.actor_restart_timeout_s}s); "
            f"the restart may still complete — retry later")

    def _drop_lineage_locked(self, oid_b: bytes):
        old = self._lineage.pop(oid_b, None)
        if old is not None:
            self._lineage_bytes -= (len(old[1][1])
                                    if old[1][0] == "inline" else 64)
        self._reconstructions.pop(oid_b, None)

    def _on_node_death(self, node_id: bytes):
        view = self.gcs.call(("list_nodes", False))
        dead = [n for n in view["nodes"] if n["node_id"] == node_id]
        if not dead:
            return
        addr = tuple(dead[0]["address"])
        self._nodes.drop(addr)
        self._shipped.pop(addr, None)
        with self._lock:
            # location cache entries naming the dead node are poison for
            # the locality scorer; deaths are rare, drop the whole cache
            self._loc_cache.clear()
        # The GCS owns restarts for plain restartable/detached actors
        # (it got their spec at creation); the driver restarts ONLY
        # PG-scheduled ones, whose placement table is driver state. Stale
        # driver-side routing drops so calls re-resolve via the GCS actor
        # table once the restart lands.
        with self._lock:
            lost = [aid for aid, a in self._actor_node.items() if a == addr]
            specs = {aid: self._actor_spec.get(aid) for aid in lost}
        for aid in lost:
            spec = specs.get(aid)
            opts = (spec[3] if spec else {}) or {}
            restartable = (opts.get("max_restarts", 0) != 0
                           or opts.get("lifetime") == "detached")
            if (spec is not None and restartable
                    and aid not in self._gcs_owned):
                threading.Thread(target=self._restart_actor_with_retry,
                                 args=(aid, spec), daemon=True,
                                 name="actor-restart").start()
            else:
                with self._lock:
                    self._actor_node.pop(aid, None)

    def _restart_actor_with_retry(self, actor_id: ActorID, spec,
                                  timeout: float = 300.0):
        """Restart pends until a node satisfying the actor's resources is
        alive (reference: gcs_actor_manager reschedules on node addition)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and not self._monitor_stop:
            try:
                self._restart_actor(actor_id, spec)
                return
            except Exception:  # noqa: BLE001 — no fitting node yet
                time.sleep(1.0)
        with self._lock:
            self._actor_node.pop(actor_id, None)

    def _restart_actor(self, actor_id: ActorID, spec):
        """Recreate the actor under its ORIGINAL id on a fitting node, so
        every handle — driver- or worker-held — keeps working unchanged.
        The decremented max_restarts is persisted back into the spec so the
        restart budget is actually enforced."""
        cls_fn_id, payload, deps, opts = spec
        opts = dict(opts or {})
        if int(opts.get("max_restarts", 0)) > 0:
            opts["max_restarts"] = int(opts["max_restarts"]) - 1
        addr = self._pick_node_strict(opts, is_actor=True)
        client = self._nodes.get(addr)
        pickled = self._ship_fn(addr, cls_fn_id)
        opts_local = self._localize_pg(opts, addr)
        client.call(("create_actor", cls_fn_id, pickled, payload,
                     deps, opts_local, None, actor_id.binary(),
                     os.urandom(16), self._driver_id))
        self._mark_shipped(addr, cls_fn_id)
        with self._lock:
            self._actor_node[actor_id] = addr
            self._actor_spec[actor_id] = (cls_fn_id, payload, deps, opts)
        self.gcs.try_call(("register_actor", actor_id.binary(),
                           {"node": addr, "state": "RESTARTED"}))

    # ------------------------------------------------------------ functions

    def register_function(self, fn) -> bytes:
        key = id(fn)
        cached = self._fn_cache.get(key)
        if cached is not None and cached[1] is fn:
            return cached[0]
        pickled = serialization.pack(fn)
        fn_id = hashlib.blake2b(pickled, digest_size=16).digest()
        with self._lock:
            self._functions[fn_id] = pickled
        self._fn_cache[key] = (fn_id, fn)
        return fn_id

    def _ship_fn(self, addr: Tuple[str, int], fn_id: bytes) -> Optional[bytes]:
        """Returns the pickled fn to attach if the node hasn't seen it.
        Callers confirm delivery with _mark_shipped AFTER the RPC succeeds."""
        if fn_id in self._shipped.setdefault(addr, set()):
            return None
        with self._lock:
            return self._functions.get(fn_id)

    def _mark_shipped(self, addr: Tuple[str, int], fn_id: bytes):
        self._shipped.setdefault(addr, set()).add(fn_id)

    # ------------------------------------------------------------ scheduling

    def _locate_deps(self, oid_bs: Sequence[bytes], fresh: bool = False
                     ) -> Dict[bytes, Tuple[List[Tuple[str, int]],
                                            Optional[int]]]:
        """Resolve locations + sizes for many ids with at most ONE GCS
        RPC (loc_get_batch), cache-first. ``fresh`` bypasses the cache —
        reconstruction dep-checks need authoritative absence, not a
        stale hit. Ids with no known location are omitted."""
        now = time.monotonic()
        ttl = config.locality_cache_ttl_s
        neg_ttl = 0.25  # a confirmed miss (producer not finished yet) is
        # re-queried at most ~4x/s — bounds the per-submission RPC rate
        # for pipelined chains without hiding publication for long
        out: Dict[bytes, Tuple[List[Tuple[str, int]], Optional[int]]] = {}
        missing: List[bytes] = []
        with self._lock:
            for b in oid_bs:
                ent = None if fresh else self._loc_cache.get(b)
                if ent is not None:
                    addrs, ts = ent
                    if addrs and now - ts < ttl:
                        out[b] = (addrs, self._obj_size.get(b))
                        continue
                    if not addrs and now - ts < neg_ttl:
                        continue  # recently confirmed absent
                missing.append(b)
        cache_hits = len(out)
        got = {}
        if missing:
            try:
                got = self.gcs.call(("loc_get_batch", list(missing)))
            except RpcError:
                got = {}
        with self._lock:
            self.locality_stats["cache_hits"] += cache_hits
            if missing:
                self.locality_stats["batched_lookups"] += 1
            for b in missing:
                ent = got.get(b)
                if ent is None:
                    self._loc_cache[b] = ([], now)  # negative entry
                    continue
                addrs = [tuple(a) for a in ent[0]]
                if ent[1] is not None:
                    self._obj_size[b] = int(ent[1])
                self._loc_cache[b] = (addrs, now)
                out[b] = (addrs, self._obj_size.get(b))
            if len(self._loc_cache) > 65536:
                self._loc_cache.clear()  # crude bound; it is only a cache
        return out

    def _pick_node_strict(self, options: dict, is_actor: bool
                          ) -> Tuple[str, int]:
        return self._pick_node(options, is_actor, strict=True)

    def _pick_node(self, options: dict, is_actor: bool,
                   exclude: Sequence[Tuple[str, int]] = (),
                   strict: bool = False,
                   dep_locs: Optional[Dict[bytes, tuple]] = None
                   ) -> Tuple[str, int]:
        options = options or {}
        req: Dict[str, float] = {}
        num_cpus = options.get("num_cpus")
        if num_cpus is None:
            num_cpus = 0.0 if is_actor else 1.0
        if num_cpus:
            req["CPU"] = float(num_cpus)
        if options.get("num_tpus"):
            req["TPU"] = float(options["num_tpus"])
        for k, v in (options.get("resources") or {}).items():
            req[k] = req.get(k, 0) + float(v)

        strategy = options.get("scheduling_strategy")
        wire = None
        if strategy is not None and hasattr(strategy, "_to_wire"):
            wire = strategy._to_wire()
        elif isinstance(strategy, tuple):
            wire = strategy
        if wire and wire[0] == "pg":
            pg = self._pgs.get(PlacementGroupID(wire[1]))
            if pg is None:
                raise PlacementGroupError("unknown placement group")
            idx = wire[2] if wire[2] is not None and wire[2] >= 0 else 0
            addr, _, _ = pg.placements[idx]
            return addr

        nodes = self._cluster_view()["nodes"]
        if wire and wire[0] == "node":
            # node affinity keeps precedence over locality / load scoring
            target, soft = wire[1], wire[2]
            tb = bytes.fromhex(target) if isinstance(target, str) else target
            for n in nodes:
                if (n["node_id"] == tb
                        and tuple(n["address"]) not in exclude):
                    return tuple(n["address"])
            if not soft:
                raise RuntimeError(
                    f"node affinity target {target!r} is not alive")
            # soft affinity: target gone, fall through to normal selection

        fit = [n for n in nodes
               if tuple(n["address"]) not in exclude
               and all(n["resources"].get(k, 0) >= v for k, v in req.items())]
        if not fit:
            if strict:
                raise RuntimeError("no node satisfies the resource request")
            # No ALIVE node's totals fit. A QUARANTINED node is cordoned
            # but not condemned — when it is the ONLY host whose totals
            # can ever satisfy the request, placing there beats parking
            # on a node whose queue would hold the task forever (the
            # quarantine shed load from a suspect node; it must not
            # strand work that is resource-bound to it). DRAINING /
            # DRAINED nodes stay excluded: they are leaving.
            if req:
                listing = self.gcs.call(("list_nodes", False))
                fit = [n for n in listing["nodes"]
                       if n["state"] == "QUARANTINED"
                       and tuple(n["address"]) not in exclude
                       and all(n["resources"].get(k, 0) >= v
                               for k, v in req.items())]
            if not fit:
                # park the task on the least-loaded node, whose queue
                # holds it until resources appear (matches the
                # reference's infeasible-task pending queue)
                fit = [n for n in nodes if tuple(n["address"]) not in exclude]
        if not fit:
            raise RuntimeError("no alive nodes in cluster")

        # locality: credit each feasible node with the bytes of
        # qualifying arguments (>= locality_min_arg_bytes) it already
        # holds, discounted by queue depth (locality_load_penalty_bytes
        # per queued task) — the owner leases from the node holding the
        # most argument bytes unless its backlog costs more than the
        # transfer saves (reference: locality-aware leasing,
        # lease_policy.h / Ownership NSDI'21)
        local_bytes: Dict[Tuple[str, int], int] = {}
        if dep_locs and not is_actor and config.locality_aware_scheduling:
            floor = config.locality_min_arg_bytes
            for addrs, nbytes in dep_locs.values():
                if nbytes is None or nbytes < floor:
                    continue
                for a in addrs:
                    a = tuple(a)
                    local_bytes[a] = local_bytes.get(a, 0) + nbytes
        penalty = config.locality_load_penalty_bytes

        # with no locality signal every eff is 0 and ordering reduces to
        # the classic (availability headroom, queue depth), then RR
        def score(n):
            addr = tuple(n["address"])
            avail_ok = all(n["avail"].get(k, 0) >= v for k, v in req.items())
            eff = (local_bytes.get(addr, 0) - n["load"] * penalty
                   if local_bytes else 0)
            return (-eff, 0 if avail_ok else 1, n["load"])
        fit.sort(key=score)
        best = [n for n in fit if score(n) == score(fit[0])]
        with self._lock:
            self._rr += 1
            chosen = tuple(best[self._rr % len(best)]["address"])
            if local_bytes:
                floor = config.locality_min_arg_bytes
                st = self.locality_stats
                if local_bytes.get(chosen, 0) >= max(local_bytes.values()):
                    st["hits"] += 1
                else:
                    st["misses"] += 1
                for addrs, nbytes in dep_locs.values():
                    if nbytes is None or nbytes < floor:
                        continue
                    if chosen in (tuple(a) for a in addrs):
                        st["bytes_local"] += nbytes   # transfer avoided
                    else:
                        st["bytes_remote"] += nbytes  # still has to move
        return chosen

    def _localize_pg(self, options: dict, addr: Tuple[str, int]) -> dict:
        """Rewrite a cluster PG scheduling strategy into the node-local one."""
        options = dict(options or {})
        strategy = options.get("scheduling_strategy")
        wire = None
        if strategy is not None and hasattr(strategy, "_to_wire"):
            wire = strategy._to_wire()
        elif isinstance(strategy, tuple):
            wire = strategy
        if wire and wire[0] == "pg":
            pg = self._pgs.get(PlacementGroupID(wire[1]))
            idx = wire[2] if wire[2] is not None and wire[2] >= 0 else 0
            node_addr, local_pg, local_idx = pg.placements[idx]
            assert node_addr == addr
            options["scheduling_strategy"] = ("pg", local_pg, local_idx)
        return options

    # ----------------------------------------------------------------- tasks

    def submit_task(self, fn_id: bytes, args: tuple, kwargs: dict,
                    num_returns=1, options: Optional[dict] = None
                    ) -> List[ObjectRef]:
        options = dict(options or {})
        streaming = num_returns == "streaming"
        if streaming:
            # single return id doubles as the stream seed; the chosen
            # node registers the stream state (node_server._do_submit)
            num_returns = 1
            options["__stream"] = True
        args2, kwargs2, deps = self._swap_top_level_refs(args, kwargs)
        payload, nested = protocol.serialize_args(args2, kwargs2, store=None)
        return_ids = [ObjectID.from_random() for _ in range(num_returns)]
        # one RPC resolves every dep's locations + sizes (cache-first);
        # feeds both the submit-time location hints and locality scoring
        dep_bs = [d.binary() for d in deps]
        dep_locs = (self._locate_deps(dep_bs)
                    if dep_bs and config.locality_aware_scheduling else {})
        locations = {}
        with self._lock:
            hints = {b: self._ref_node.get(b) for b in dep_bs}
            sizes = {b: self._obj_size.get(b) for b in dep_bs}
        for b in dep_bs:
            hint = hints[b]
            addrs, nbytes = dep_locs.get(b, ([], None))
            if hint is not None and hint not in addrs:
                # the owner hint covers deps the directory hasn't seen
                # yet (unfinished producers): the submitting node knows
                # where the object WILL appear
                addrs = list(addrs) + [hint]
            if nbytes is None:
                nbytes = sizes[b]
            if addrs:
                dep_locs[b] = (addrs, nbytes)
                locations[b] = tuple(addrs[0]) if hint is None else hint
        msg_tail = ([d.binary() for d in deps],
                    [r.binary() for r in nested],
                    [r.binary() for r in return_ids])
        tried: List[Tuple[str, int]] = []
        # One nonce per LOGICAL submission. The transport layer retries a
        # lost reply on the SAME node, where the nonce dedups (exactly-
        # once); reconstruction mints a new nonce because re-execution
        # there is deliberate. The failover loop below only fires after
        # the same-node retry failed too — i.e. the node is unreachable —
        # so cross-node re-submission is at-least-once under a network
        # partition (the reference's task max_retries has the same
        # semantics).
        nonce = os.urandom(16)
        while True:
            # spillback failover re-scores with the tried nodes excluded
            addr = self._pick_node(options, is_actor=False, exclude=tried,
                                   dep_locs=dep_locs)
            options2 = self._localize_pg(options, addr)
            pickled_fn = self._ship_fn(addr, fn_id)
            try:
                self._nodes.get(addr).call(
                    ("submit", fn_id, pickled_fn, payload, *msg_tail,
                     options2, locations, nonce, self._driver_id))
                break
            except RpcError:
                # stale view: the node died but isn't marked DEAD yet
                tried.append(addr)
                if len(tried) >= 4:
                    raise
                self._cluster_view(force=True)
        self._mark_shipped(addr, fn_id)
        if streaming:
            # No lineage for streams: replay-after-worker-death happens on
            # the owning node (skip-aware requeue); a lost index object is
            # not reconstructable and raises ObjectLostError instead.
            with self._lock:
                self._ref_node[return_ids[0].binary()] = addr
            return [ObjectRef(rid, core=self) for rid in return_ids]
        lineage = (fn_id, payload, [d.binary() for d in deps],
                   [r.binary() for r in nested],
                   [r.binary() for r in return_ids], options)
        cost = len(payload[1]) if payload[0] == "inline" else 64
        with self._lock:
            for rid in return_ids:
                self._ref_node[rid.binary()] = addr
                self._lineage[rid.binary()] = lineage
                # cost accrues per entry (eviction also subtracts per entry)
                self._lineage_bytes += cost
            # byte-budgeted lineage (reference evicts lineage the same way:
            # max_lineage_bytes); oldest entries lose reconstructability
            while (self._lineage_bytes > config.lineage_max_bytes
                   and self._lineage):
                _, old = self._lineage.popitem(last=False)
                self._lineage_bytes -= (len(old[1][1])
                                        if old[1][0] == "inline" else 64)
        return [ObjectRef(rid, core=self) for rid in return_ids]

    def _swap_top_level_refs(self, args, kwargs):
        deps: List[ObjectID] = []

        def swap(v):
            if isinstance(v, ObjectRef):
                deps.append(v.id)
                return protocol._TopLevelDep(v.binary())
            return v

        return (tuple(swap(a) for a in args),
                {k: swap(v) for k, v in kwargs.items()}, deps)

    # --------------------------------------------------------------- objects

    def put_object(self, value: Any) -> ObjectRef:
        pickled, views, total = serialization.serialize(value)
        buf = bytearray(total)
        serialization.write_container(memoryview(buf), pickled, views)
        oid_b = self._nodes.get(self._home).call(
            ("put", bytes(buf), None, self._driver_id))
        with self._lock:
            self._ref_node[oid_b] = self._home
            # the driver knows its own puts' size and home before the
            # node's batched loc_add lands — seed the scorer's tables
            self._obj_size[oid_b] = total
            self._loc_cache[oid_b] = ([self._home], time.monotonic())
        return ObjectRef(ObjectID(oid_b), core=self)

    def _route(self, oid_b: bytes, default=None):
        """Locked single-probe read of the owner-routing table. Every
        read of _ref_node goes through here (or holds _lock inline) so
        routing lookups never observe a torn compound update."""
        with self._lock:
            return self._ref_node.get(oid_b, default)

    def get_objects(self, refs: List[ObjectRef],
                    timeout: Optional[float] = None) -> List[Any]:
        out: Dict[bytes, Any] = {}
        groups: Dict[Tuple[str, int], List[bytes]] = {}
        for ref in refs:
            b = ref.binary()
            # rtpu-lint: disable=L7 — deliberate lock-free tombstone
            # probe on the hot get() path: note_freed only ever ADDS
            # tombstones, a dict-membership read is GIL-atomic, and this
            # loop blocks on ev.wait() so holding self._lock here would
            # stall every other driver thread (and violate L2)
            if b in self._freed:
                raise ObjectLostError(
                    f"object {b.hex()} was freed by ray_tpu.free() and is "
                    f"not reconstructable")
            if b in self._local:
                ev, cell = self._local[b]
                if not ev.wait(timeout):
                    raise GetTimeoutError("get() timed out")
                out[b] = cell[0]
                continue
            addr = self._route(b, self._home)
            groups.setdefault(addr, []).append(b)
        errs: List[BaseException] = []

        def fetch(addr, oids):
            try:
                allow_shm = (self._home_store is not None
                             and addr == self._home)
                payloads = self._nodes.get(addr).call(
                    ("get", oids, timeout, allow_shm))
                for b, payload in payloads.items():
                    try:
                        out[b] = self._decode(payload)
                    except Exception:  # noqa: BLE001
                        if payload[0] != "shm":
                            raise
                        # shm fast path raced a spill: re-request the
                        # materialized bytes over RPC
                        p2 = self._nodes.get(addr).call(
                            ("get", [b], timeout, False))
                        out[b] = self._decode(p2[b])
            except RpcError:
                # node died: any other location? (GCS directory) — one
                # batched lookup covers the whole failed group
                batched = (self._locate_deps(oids, fresh=True)
                           if len(oids) > 1 else {})
                for b in oids:
                    try:
                        out[b] = self._fetch_anywhere(
                            b, timeout, locs=batched.get(b, (None,))[0])
                    except BaseException as e:  # noqa: BLE001
                        errs.append(e)
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        if len(groups) == 1:
            ((addr, oids),) = groups.items()
            fetch(addr, oids)
        elif groups:
            threads = [threading.Thread(target=fetch, args=(a, o))
                       for a, o in groups.items()]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        if errs:
            raise errs[0]
        values = []
        for ref in refs:
            v = out[ref.binary()]
            values.append(protocol.raise_if_error(v))
        return values

    def _decode(self, payload):
        kind, data = payload
        if kind == "shm" and self._home_store is not None:
            return protocol.shm_unpack(self._home_store, ObjectID(data))
        return serialization.unpack(data)

    def _fetch_anywhere(self, oid_b: bytes, timeout: Optional[float],
                        locs=None):
        if not locs:
            # single-id path keeps loc_get's short blocking wait (the
            # object may be mid-publication on its new node)
            locs = self.gcs.call(("loc_get", oid_b, 2.0))
        for addr in locs:
            try:
                data = self._nodes.get(tuple(addr)).call(("fetch", oid_b))
            except RpcError:
                continue
            if data is not None:
                with self._lock:
                    self._ref_node[oid_b] = tuple(addr)
                return self._decode(data)
        # a worker-freed object must stay dead: check the published
        # tombstone before resurrecting through lineage (the driver-side
        # _freed set only covers driver-initiated frees)
        try:
            freed = self.gcs.call(("freed_check", oid_b))
        except RpcError:
            freed = False
        if freed:
            with self._lock:
                from ray_tpu.core.runtime import note_freed
                note_freed(self._freed, (oid_b,))
            raise ObjectLostError(
                f"object {oid_b.hex()} was freed by ray_tpu.free() "
                f"and is not reconstructable")
        # no surviving copy: reconstruct through lineage by resubmitting the
        # creating task (recursively reconstructing lost deps first)
        if self._reconstruct(oid_b):
            payloads = self._nodes.get(self._route(oid_b)).call(
                ("get", [oid_b], timeout, False))
            return self._decode(payloads[oid_b])
        raise ObjectLostError(
            f"object {oid_b.hex()} is lost (owner node died, no other copy "
            f"exists, and no lineage is available to reconstruct it)")

    def _reconstruct(self, oid_b: bytes, depth: int = 0) -> bool:
        """Resubmit the creating task of a lost object. Returns True when a
        resubmission was issued (the object will materialize on the new
        node). Bounded per object by max_reconstructions."""
        if depth > 10:
            return False
        # "free means dead": an eagerly-freed object (driver- OR
        # worker-originated) must never be resurrected, directly or as a
        # recursively-reconstructed dependency
        with self._lock:
            freed = oid_b in self._freed
            lineage = self._lineage.get(oid_b)
            n = self._reconstructions.get(oid_b, 0)
        if freed or lineage is None or n >= config.max_reconstructions:
            return False
        try:
            # the GCS freed-set is authoritative for worker-originated
            # frees the driver hasn't drained yet
            if self.gcs.call(("freed_check", oid_b)):
                return False
        except RpcError:
            pass
        fn_id, payload, deps_b, nested_b, return_ids_b, options = lineage
        # deps that are lost themselves get reconstructed first; with
        # several deps one loc_get_batch replaces the per-id loop
        # (fresh: a stale cache hit here would skip reviving a lost dep)
        if len(deps_b) > 1:
            present = self._locate_deps(deps_b, fresh=True)
            missing = [b for b in deps_b
                       if not present.get(b, ((), None))[0]]
        else:
            missing = [b for b in deps_b
                       if not self.gcs.call(("loc_get", b, 0.0))]
        for dep_b in missing:
            if not self._reconstruct(dep_b, depth + 1):
                return False
        # the cluster view can lag node death by a heartbeat timeout;
        # fail over across candidate nodes
        tried: List[Tuple[str, int]] = []
        for _ in range(4):
            try:
                addr = self._pick_node(dict(options or {}), is_actor=False,
                                       exclude=tried)
            except RuntimeError:
                return False
            pickled_fn = self._ship_fn(addr, fn_id)
            options2 = self._localize_pg(dict(options or {}), addr) \
                if (options or {}).get("scheduling_strategy") \
                else dict(options or {})
            try:
                # fresh nonce: reconstruction deliberately RE-executes the
                # creating task, it must never be deduped against the
                # original submission
                self._nodes.get(addr).call(
                    ("submit", fn_id, pickled_fn, payload, deps_b, nested_b,
                     return_ids_b, options2, None, os.urandom(16),
                     self._driver_id))
                break
            except RpcError:
                tried.append(addr)
                self._cluster_view(force=True)
        else:
            return False
        self._mark_shipped(addr, fn_id)
        with self._lock:
            for rid_b in return_ids_b:
                self._ref_node[rid_b] = addr
                self._reconstructions[rid_b] = (
                    self._reconstructions.get(rid_b, 0) + 1)
        return True

    def wait(self, refs: List[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        deadline = None if timeout is None else time.monotonic() + timeout
        ready_set: set = set()
        while True:
            groups: Dict[Tuple[str, int], List[bytes]] = {}
            for ref in refs:
                b = ref.binary()
                if b in ready_set:
                    continue
                if b in self._local:
                    if self._local[b][0].is_set():
                        ready_set.add(b)
                    continue
                groups.setdefault(self._route(b, self._home),
                                  []).append(b)
            if len(ready_set) >= num_returns:
                break
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                break
            step = 0.2 if remaining is None else max(0.0, min(0.2, remaining))
            if not groups:
                # only driver-local sentinels left: block on one of them
                # instead of spinning
                unresolved = [self._local[r.binary()][0] for r in refs
                              if r.binary() in self._local
                              and not self._local[r.binary()][0].is_set()]
                if unresolved:
                    unresolved[0].wait(step)
                else:
                    time.sleep(min(0.01, step))
                continue

            def poll(addr, oids):
                try:
                    r, _ = self._nodes.get(addr).call(
                        ("wait", oids, len(oids), step))
                    ready_set.update(r)
                # rtpu-lint: disable=L4 — one node failing its poll slice
                # (dying, restarting) must not fail the whole wait(); its
                # objects just stay not-ready until the next round
                except Exception:  # noqa: BLE001
                    pass

            threads = [threading.Thread(target=poll, args=(a, o))
                       for a, o in groups.items()]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        ready = [r for r in refs if r.binary() in ready_set][:num_returns]
        ready_ids = {r.binary() for r in ready}
        rest = [r for r in refs if r.binary() not in ready_ids]
        return ready, rest

    def as_future(self, ref: ObjectRef):
        import asyncio

        loop = asyncio.get_event_loop()
        fut = loop.create_future()

        def run():
            try:
                v = self.get_objects([ref], timeout=None)[0]
            except BaseException as e:  # noqa: BLE001
                loop.call_soon_threadsafe(fut.set_exception, e)
                return
            loop.call_soon_threadsafe(fut.set_result, v)

        threading.Thread(target=run, daemon=True).start()
        return fut

    # ---------------------------------------------------------------- actors

    def create_actor(self, cls_fn_id: bytes, args: tuple, kwargs: dict,
                     opts: Optional[dict] = None) -> ActorID:
        opts = dict(opts or {})
        args2, kwargs2, deps = self._swap_top_level_refs(args, kwargs)
        payload, _ = protocol.serialize_args(args2, kwargs2, store=None)
        addr = self._pick_node(opts, is_actor=True)
        opts2 = self._localize_pg(opts, addr)
        pickled_cls = self._ship_fn(addr, cls_fn_id)
        locations = {d.binary(): self._route(d.binary()) for d in deps}
        locations = {k: v for k, v in locations.items() if v is not None}
        dep_b = [d.binary() for d in deps]
        # driver-chosen actor id + per-request nonce: a retried
        # create_actor whose reply was lost dedups server-side
        # (exactly-once apply), while restarts under the same id mint a
        # new nonce and re-apply
        actor_id_b = ActorID.from_random().binary()
        self._nodes.get(addr).call(
            ("create_actor", cls_fn_id, pickled_cls, payload, dep_b, opts2,
             locations, actor_id_b, os.urandom(16), self._driver_id))
        self._mark_shipped(addr, cls_fn_id)
        actor_id = ActorID(actor_id_b)
        with self._lock:
            self._actor_node[actor_id] = addr
            self._actor_opts[actor_id] = opts.get("method_opts", {})
            # keep the ORIGINAL opts (cluster-level PG strategy): restart
            # re-localizes against whichever node it lands on
            self._actor_spec[actor_id] = (cls_fn_id, payload, dep_b, opts)
        # restartable/detached actors hand their restart FSM to the GCS
        # (reference: gcs_actor_manager.h:278) so they outlive this
        # driver. PG-scheduled actors stay driver-restarted: the PG
        # placement table is driver state.
        restartable = (opts.get("max_restarts", 0) != 0
                       or opts.get("lifetime") == "detached")
        if restartable and not opts.get("scheduling_strategy"):
            try:
                with self._lock:
                    pickled_full = self._functions.get(cls_fn_id)
                if pickled_full is not None:
                    self.gcs.call(("register_fn", cls_fn_id, pickled_full))
                # full opts INCLUDING method_opts: after a GCS-owned
                # restart, handles re-derived via get_actor() must keep
                # per-method options (num_returns overrides etc.)
                self.gcs.call(("register_actor_spec", actor_id_b, {
                    "cls_fn_id": cls_fn_id, "payload": payload,
                    "deps": dep_b, "opts": opts,
                    "name": opts.get("name"),
                    # owner: if this driver dies, the GCS stops
                    # restarting the actor unless it is detached
                    "owner": self._driver_id,
                }))
                with self._lock:
                    self._gcs_owned.add(actor_id)
            # rtpu-lint: disable=L4 — registration failed (GCS outage
            # window): the driver keeps restart authority — never leave
            # the actor with NO restart owner
            except Exception:  # noqa: BLE001
                pass
        return actor_id

    def _actor_addr(self, actor_id: ActorID) -> Tuple[str, int]:
        with self._lock:
            addr = self._actor_node.get(actor_id)
        if addr is None:
            info = self.gcs.call(("list_actors",)).get(actor_id.binary())
            if info is None or "node" not in info:
                raise ActorDiedError(f"unknown actor {actor_id}")
            addr = tuple(info["node"])
            with self._lock:
                self._actor_node[actor_id] = addr
        return addr

    def _actor_call_with_retry(self, actor_id: ActorID, msg_fn):
        """Run an actor-routed RPC; on stale routing (node died, actor was
        restarted elsewhere) re-resolve via the GCS actor table and retry.
        When the ``actor_state`` channel says a restart is underway, the
        retry first waits (bounded) for the new incarnation so the call
        lands on it instead of surfacing a transient death."""
        addr = self._actor_addr(actor_id)
        try:
            return addr, self._nodes.get(addr).call(msg_fn(addr))
        except (RpcError, ActorDiedError):
            with self._lock:
                self._actor_node.pop(actor_id, None)
            self._drain_actor_state_channel()
            self._await_actor_restart(actor_id)
            addr = self._actor_addr(actor_id)
            return addr, self._nodes.get(addr).call(msg_fn(addr))

    def submit_actor_task(self, actor_id: ActorID, method: str, args: tuple,
                          kwargs: dict, num_returns=1,
                          options: Optional[dict] = None
                          ) -> List[ObjectRef]:
        streaming = num_returns == "streaming"
        if streaming:
            num_returns = 1
        args2, kwargs2, deps = self._swap_top_level_refs(args, kwargs)
        payload, nested = protocol.serialize_args(args2, kwargs2, store=None)
        return_ids = [ObjectID.from_random() for _ in range(num_returns)]
        msg = ("actor_call", actor_id.binary(), method, payload,
               [d.binary() for d in deps], [r.binary() for r in nested],
               [r.binary() for r in return_ids], os.urandom(16),
               self._driver_id, streaming, dict(options or {}))
        try:
            addr, _ = self._actor_call_with_retry(actor_id, lambda a: msg)
        except RpcError as e:
            raise ActorDiedError(
                f"actor {actor_id} node is unreachable: {e}") from e
        with self._lock:
            for rid in return_ids:
                self._ref_node[rid.binary()] = addr
        return [ObjectRef(rid, core=self) for rid in return_ids]

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        if no_restart:
            with self._lock:
                self._actor_spec.pop(actor_id, None)
            # the GCS must not resurrect an explicitly killed actor
            self.gcs.try_call(("drop_actor_spec", actor_id.binary()))
        try:
            self._actor_call_with_retry(
                actor_id,
                lambda a: ("kill_actor", actor_id.binary(), no_restart))
        # rtpu-lint: disable=L4 — kill of an already-dead/unreachable
        # actor is the desired end state, not a lost signal: there is
        # nothing left to kill and no caller waiting on a result
        except (RpcError, ActorDiedError):
            pass

    def get_actor_method_opts(self, actor_id: ActorID) -> dict:
        opts = self._actor_opts.get(actor_id)
        if opts is not None:
            return opts
        _, opts = self._actor_call_with_retry(
            actor_id, lambda a: ("actor_opts", actor_id.binary()))
        self._actor_opts[actor_id] = opts
        return opts

    def get_named_actor(self, name: str) -> ActorID:
        entry = self.gcs.call(("get_named_actor", name))
        if entry is None:
            raise ValueError(f"no actor named {name!r}")
        actor_id = ActorID(entry[0])
        with self._lock:
            self._actor_node.setdefault(actor_id, tuple(entry[1]))
        return actor_id

    def get_actor_handle(self, name: str):
        from ray_tpu.core.actor import ActorHandle

        aid = self.get_named_actor(name)
        return ActorHandle(aid, self.get_actor_method_opts(aid))

    # ------------------------------------------------------ placement groups

    def create_placement_group(self, bundles, strategy, name
                               ) -> PlacementGroup:
        pg_id = PlacementGroupID.from_random()
        cpg = _ClusterPG(pg_id, bundles, strategy, name)
        nodes = self._cluster_view(force=True)["nodes"]
        if not nodes:
            raise RuntimeError("no alive nodes")

        def fits(node, bundle_list):
            need: Dict[str, float] = {}
            for b in bundle_list:
                for k, v in b.items():
                    need[k] = need.get(k, 0) + v
            return all(node["resources"].get(k, 0) >= v
                       for k, v in need.items())

        assignments: Dict[Tuple[str, int], List[int]] = {}
        if strategy in ("PACK", "STRICT_PACK"):
            host = next((n for n in nodes if fits(n, bundles)), None)
            if host is None:
                if strategy == "STRICT_PACK":
                    raise ValueError(
                        "no node can hold all STRICT_PACK bundles")
                host = max(nodes, key=lambda n: sum(n["avail"].values()))
            assignments[tuple(host["address"])] = list(range(len(bundles)))
        else:  # SPREAD / STRICT_SPREAD: round-robin over fitting nodes
            order = sorted(nodes, key=lambda n: n["load"])
            if strategy == "STRICT_SPREAD" and len(order) < len(bundles):
                raise ValueError(
                    f"STRICT_SPREAD needs {len(bundles)} nodes, "
                    f"cluster has {len(order)}")
            for i, bundle in enumerate(bundles):
                cand = [n for n in order if fits(n, [bundle])] or order
                node = cand[i % len(cand)]
                assignments.setdefault(tuple(node["address"]), []).append(i)

        placements: List[Optional[Tuple]] = [None] * len(bundles)
        created: List[Tuple[Tuple[str, int], bytes]] = []
        try:
            for addr, idxs in assignments.items():
                sub = [bundles[i] for i in idxs]
                local_pg_b = self._nodes.get(addr).call(
                    ("pg", "create", sub, "PACK", None))
                created.append((addr, local_pg_b))
                cpg.node_pgs[addr] = local_pg_b
                for local_idx, i in enumerate(idxs):
                    placements[i] = (addr, local_pg_b, local_idx)
        except Exception:
            for addr, local_pg_b in created:
                try:
                    # rtpu-lint: disable=L9 — per-node rollback fan-out,
                    # not a re-send: each iteration targets a DIFFERENT
                    # node, and removing an already-removed local group
                    # is a no-op on the node
                    self._nodes.get(addr).call(("pg", "remove", local_pg_b))
                # rtpu-lint: disable=L4 — best-effort rollback of the
                # partially created group; the original placement error
                # re-raises below regardless
                except Exception:  # noqa: BLE001
                    pass
            raise
        cpg.placements = placements
        with self._lock:
            self._pgs[pg_id] = cpg
        return PlacementGroup(pg_id, bundles)

    def _cluster_pg(self, pg_id: PlacementGroupID) -> _ClusterPG:
        pg = self._pgs.get(pg_id)
        if pg is None:
            raise PlacementGroupError(f"unknown placement group {pg_id}")
        return pg

    def wait_placement_group(self, pg_id: PlacementGroupID,
                             timeout: float) -> bool:
        pg = self._cluster_pg(pg_id)
        deadline = time.monotonic() + timeout
        for addr, local_pg_b in pg.node_pgs.items():
            remaining = max(0.0, deadline - time.monotonic())
            if not self._nodes.get(addr).call(
                    ("pg", "wait", local_pg_b, remaining)):
                return False
        return True

    def placement_group_ready_ref(self, pg_id: PlacementGroupID) -> ObjectRef:
        oid = ObjectID.from_random()
        ev = threading.Event()
        cell: list = [None]
        self._local[oid.binary()] = (ev, cell)

        def run():
            try:
                ok = self.wait_placement_group(pg_id, timeout=3600.0)
                cell[0] = ok
            except BaseException as e:  # noqa: BLE001
                cell[0] = protocol.ErrorValue(e)
            ev.set()

        threading.Thread(target=run, daemon=True).start()
        return ObjectRef(oid, core=self)

    def placement_group_chips(self, pg_id: PlacementGroupID,
                              index: int) -> List[int]:
        pg = self._cluster_pg(pg_id)
        addr, local_pg_b, local_idx = pg.placements[index]
        return self._nodes.get(addr).call(("pg", "chips", local_pg_b,
                                           local_idx))

    def remove_placement_group(self, pg_id: PlacementGroupID):
        pg = self._pgs.get(pg_id)
        if pg is None:
            return
        for addr, local_pg_b in pg.node_pgs.items():
            try:
                # rtpu-lint: disable=L9 — per-node fan-out, not a
                # re-send: each iteration removes a DIFFERENT node's
                # slice, and a double remove is a no-op on the node
                self._nodes.get(addr).call(("pg", "remove", local_pg_b))
            # rtpu-lint: disable=L4 — removal on a dead/unreachable node
            # is moot (its reservations died with it); remove the rest
            except Exception:  # noqa: BLE001
                pass
        with self._lock:
            self._pgs.pop(pg_id, None)

    def placement_group_table(self) -> Dict[str, dict]:
        out = {}
        with self._lock:
            pgs = list(self._pgs.items())
        for pg_id, pg in pgs:
            out[pg_id.hex()] = {
                "bundles": pg.bundles,
                "strategy": pg.strategy,
                "name": pg.name,
                "nodes": [list(a) for a in pg.node_pgs],
            }
        return out

    # -------------------------------------------------------------- misc api

    def cancel_task(self, ref: ObjectRef, force: bool = False):
        addr = self._route(ref.binary(), self._home)
        try:
            self._nodes.get(addr).call(("cancel", ref.binary(), force))
        except RpcError:
            pass

    # ---------------------------------------------------- streaming returns

    def stream_owner(self, seed: bytes) -> Optional[Tuple[str, int]]:
        """Node address owning a stream's state (captured into the
        ObjectRefGenerator so it keeps routing after cross-node pickling)."""
        return self._route(seed)

    def stream_next(self, seed: bytes, index: int,
                    timeout: Optional[float] = None, owner=None):
        """Driver-side consumption: poll the owning node in bounded slices
        (same contract as Runtime.stream_next — ("ref", rid_b) or
        ("end", count), ObjectTimeoutError past the deadline)."""
        addr = tuple(owner) if owner else self._route(seed, self._home)
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            slice_s = 0.2
            if deadline is not None:
                # always probe at least once (timeout=0 is a poll)
                slice_s = max(0.0, min(slice_s,
                                       deadline - time.monotonic()))
            reply = self._nodes.get(addr).call(
                ("stream_next", seed, index, max(1, int(slice_s * 1000))))
            if reply[0] == "pending":
                if (deadline is not None
                        and time.monotonic() >= deadline):
                    raise ObjectTimeoutError(
                        f"stream_next timed out waiting for index {index} "
                        f"of stream {seed.hex()}")
                continue
            if reply[0] == "ref":
                # the owner sealed the index object locally; route gets
                with self._lock:
                    self._ref_node[reply[1]] = addr
            return reply

    def stream_consumed(self, seed: bytes, index: int, owner=None):
        """Advance the consumer watermark (backpressure credit) on the
        owning node; best-effort — a lost credit only delays the producer
        by one poll slice."""
        addr = tuple(owner) if owner else self._route(seed, self._home)
        try:
            # rtpu-lint: disable=L9 — the credit is a MONOTONIC
            # watermark: the producer takes max(old, new), so a lost or
            # double-applied advance can only under-report consumption
            # (one poll-slice stall), never corrupt the stream
            self._nodes.get(addr).call(("stream_consumed", seed, index))
        except RpcError:
            pass

    def kv_op(self, op: str, key: str, value=None):
        return self.gcs.call(("kv", op, key, value))

    def pubsub_op(self, op: str, channel: str, arg=None,
                  timeout: float = 0.0):
        """Cluster-wide pubsub IS the GCS channel plane."""
        if op == "publish":
            return self.gcs.call(("publish", channel, arg))
        if op == "poll":
            return self.gcs.call(("poll", channel, int(arg or 0), timeout))
        raise ValueError(op)

    def free_objects(self, oid_bytes_list: List[bytes]) -> int:
        """Fan eager deletion out to every node holding a copy; returns
        the count of UNIQUE objects freed anywhere."""
        freed: set = set()
        # full listing, not the schedulable view: DRAINING/QUARANTINED
        # nodes are cordoned from NEW placement but still hold copies —
        # a free that skips them leaves stale bytes to be served later
        listing = self.gcs.call(("list_nodes", False))
        addrs = {tuple(n["address"]) for n in listing["nodes"]
                 if n["state"] != "DEAD"}
        for addr in addrs:
            try:
                # rtpu-lint: disable=L9 — per-node fan-out, not a
                # re-send; free of an unknown/tombstoned id is a no-op,
                # and the freed_add tombstone published below is the
                # authority a missed node converges on via _drain_freed
                freed.update(self._nodes.get(addr).call(
                    ("free", oid_bytes_list)) or [])
            except RpcError:
                continue
        # clear lineage ONLY for ids actually freed: free of an
        # unresolved/unknown id is a no-op and must not destroy a live
        # object's reconstructability (symmetric byte accounting with the
        # insertion/eviction paths)
        from ray_tpu.core.runtime import note_freed

        if freed:
            # publish tombstones so node fetch loops and reconstruction
            # refuse these ids even when the freeing driver exits
            try:
                self.gcs.call(("freed_add", list(freed)))
            except RpcError:
                pass
        with self._lock:
            note_freed(self._freed, freed)
            for b in freed:
                # drop the location hint too — the periodic-free pattern
                # (router load reports) must not grow _ref_node unboundedly
                self._ref_node.pop(b, None)
                self._loc_cache.pop(b, None)
                self._obj_size.pop(b, None)
            for b in freed:
                self._drop_lineage_locked(b)
        return len(freed)

    # ---- runtime_env packages: content-addressed blobs in the GCS KV,
    # pulled lazily by each node (reference: GCS package store + per-node
    # runtime-env agent download)

    def register_package(self, pkg_hash: str, data: bytes) -> None:
        registered = getattr(self, "_registered_pkgs", None)
        if registered is None:
            registered = self._registered_pkgs = set()
        if pkg_hash in registered:
            return
        key = f"pkg:{pkg_hash}"
        # exists-check: never pull the blob back just to test presence
        if not self.kv_op("exists", key):
            self.kv_op("put", key, data)
        registered.add(pkg_hash)

    def prepare_runtime_env(self, runtime_env):
        from ray_tpu.core import runtime_env as _re

        return _re.prepare(self, runtime_env)

    def cluster_resources(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for n in self._cluster_view(force=True)["nodes"]:
            for k, v in n["resources"].items():
                total[k] = total.get(k, 0) + v
        return total

    def nodes(self) -> List[dict]:
        return self._cluster_view(force=True)["nodes"]

    def drain_node(self, node_id: bytes) -> bool:
        """Begin planned removal of a node (ALIVE -> DRAINING): the
        scheduler cordon is immediate, actors migrate via the GCS
        restart FSM, and running tasks get node_drain_grace_s before
        the node is declared DRAINED and can deregister cleanly."""
        return bool(self.gcs.call(("drain_node", node_id)))

    def node_states(self) -> Dict[str, str]:
        """{node_id hex: lifecycle state} for every node the GCS knows
        (including DRAINING/QUARANTINED/DRAINED/DEAD ones the scheduling
        view filters out)."""
        listing = self.gcs.call(("list_nodes", False))
        return {n["node_id"].hex(): n["state"] for n in listing["nodes"]}

    def wait_for_workers(self, count: Optional[int] = None,
                         timeout: Optional[float] = None):
        return True  # nodes bring their own pools up

    def shutdown(self):
        self._monitor_stop = True
        # clean exit: no death event, nodes keep objects until eviction
        self.gcs.try_call(("unregister_driver", self._driver_id))
        if self._home_store is not None:
            try:
                self._home_store.close()
            # rtpu-lint: disable=L4 — shutdown path: keep tearing the
            # rest of the cluster down whatever state the store is in
            except Exception:  # noqa: BLE001
                pass
        self._nodes.close_all()
        self.gcs.close()
        # reap the death-watch: close() wakes any call it has parked in
        # the ride-through loop, so the thread exits within one poll
        # period — without the join it outlives shutdown() and bleeds
        # connect-retry activity into whatever runs next (the seeded
        # interleave tracer sees that as a schedule mismatch)
        self._monitor.join(timeout=5.0)
