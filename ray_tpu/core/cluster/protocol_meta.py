"""WIRE_CONTRACT — the single source of truth for wire-level retry,
idempotency, and durability-resync classification.

Every op a client can put on a wire — the GCS ``_op_*`` dispatch arms,
the node server ``_op_*`` dispatch arms, and the driver<->worker
``MSG_*``/``REQ_*`` tags from ``core/protocol.py`` — has exactly one
entry here, keyed by its wire string (``msg[0]``). The transport retry
weave (rpc.py), the HA ride-through buffer (ha.py), and the L9/L10 lint
rules all derive from this table; nothing else in the tree may hardcode
a retry whitelist.

The four classifications:

- ``IDEMPOTENT`` — a pure read or poll. Applying it any number of times
  returns the same answer and changes nothing; re-send freely.
- ``RETRY_AFTER_APPLY`` — a set-style / last-writer-wins write where
  apply-twice == apply-once (register_node replaces the row wholesale,
  loc_add inserts into a set, cancel of a finished task is a no-op).
  Safe to re-send even when the first request may already have been
  applied (reply lost).
- ``dedup_keyed("<key>")`` — exactly-once via a server-side dedup
  structure keyed on a caller-minted id (the ``nonce`` argument,
  absorbed by ``NodeServer._dedup``/``_applied``). Re-delivery returns
  the original result instead of re-running the side effect, so the
  transport may retry it like an idempotent op — but ONLY against a
  server that holds the dedup table (same-address retry).
- ``NON_RETRYABLE`` — everything else: a blind re-send after a lost
  reply risks running the side effect twice (double pubsub event,
  double refcount decrement, double merge). A lost reply surfaces as
  ``RpcError`` with ``maybe_applied=True`` and the caller decides.

Classifying a new op: start from the server-side apply body. If it only
reads, ``IDEMPOTENT``. If re-applying the same arguments cannot change
the outcome (pure overwrite / set-insert / idempotent state machine),
``RETRY_AFTER_APPLY``. If the handler runs arbitrary side effects but
takes a nonce through ``_dedup``, ``dedup_keyed("nonce")``. Anything
else — including "probably fine" — is ``NON_RETRYABLE`` until a netem
dup/lost_reply sweep (tests/test_netem.py) proves otherwise. L9 fails
the build on an unclassified op.

NOTE on conservatism: the retry-safe subset of this table is pinned
byte-for-byte to the whitelist the transport has always used
(tests/test_netem.py::test_wire_contract_whitelist_parity), so hoisting
the table out of rpc.py changed no runtime behavior. Several ops below
are marked ``NON_RETRYABLE`` although a case can be made for retrying
them (``node_drained`` and ``stream_consumed`` are idempotent state
transitions; ``free`` tombstones make double-frees no-ops); promoting
one is a semantic change that must ride its own netem sweep, not this
table's refactor.

Driver<->worker ``MSG_*``/``REQ_*`` tags travel over pipes with NO
retry machinery — a broken pipe is a worker death, never a re-send — so
the pipe-only tags are all ``NON_RETRYABLE`` by policy regardless of
semantic idempotence (the classification is inert there; it exists so
L9 can prove table totality). Tags that SHARE a wire string with an RPC
op (``get``, ``submit``, ``actor_call``, ``create_actor``, ``wait``,
``kv``, ``cancel``, ``pg``, ``stream_next``) carry the RPC
classification: the transport weave keys on ``msg[0]`` alone, so one
wire string can only ever have one contract.
"""

from __future__ import annotations

from typing import Dict

IDEMPOTENT = "idempotent"
RETRY_AFTER_APPLY = "retry_after_apply"
NON_RETRYABLE = "non_retryable"
#: kv's contract depends on the sub-op (msg[1]) — see KV_SUBOP_CONTRACT
PER_SUBOP = "per_subop"


def dedup_keyed(key: str) -> str:
    """Exactly-once through a server-side dedup table keyed on the
    caller-minted ``key`` argument (NodeServer._dedup / _applied)."""
    return "dedup_keyed:" + key


def is_dedup_keyed(classification: str) -> bool:
    return classification.startswith("dedup_keyed:")


def dedup_key(classification: str) -> str:
    """The caller-minted id field a dedup_keyed op is keyed on."""
    return classification.split(":", 1)[1]


def retry_safe(classification: str) -> bool:
    """True when re-sending is safe even if the server already applied
    the request once (at-least-once indistinguishable from
    exactly-once)."""
    return (classification in (IDEMPOTENT, RETRY_AFTER_APPLY)
            or is_dedup_keyed(classification))


WIRE_CONTRACT: Dict[str, str] = {
    # ------------------------------------------------ reads / polls
    "ping": IDEMPOTENT,
    "status": IDEMPOTENT,
    "state": IDEMPOTENT,
    "stack_dump": IDEMPOTENT,
    "task_events": IDEMPOTENT,
    "list_logs": IDEMPOTENT,
    "get_log": IDEMPOTENT,
    "list_nodes": IDEMPOTENT,
    "wait_nodes": IDEMPOTENT,      # blocking read; waits, writes nothing
    "deaths_since": IDEMPOTENT,
    "driver_deaths_since": IDEMPOTENT,
    "freed_check": IDEMPOTENT,
    "get_named_actor": IDEMPOTENT,
    "list_actors": IDEMPOTENT,
    "loc_get": IDEMPOTENT,
    "loc_get_batch": IDEMPOTENT,
    "poll": IDEMPOTENT,            # long-poll read; cursor is client-side
    "get_fn": IDEMPOTENT,
    "gcs_info": IDEMPOTENT,
    "get": IDEMPOTENT,             # node op + REQ_GET (same wire string)
    "fetch": IDEMPOTENT,
    "fetch_size": IDEMPOTENT,
    "fetch_range": IDEMPOTENT,
    "has": IDEMPOTENT,
    "wait": IDEMPOTENT,            # node op + REQ_WAIT
    "actor_opts": IDEMPOTENT,
    # ---------------- set / last-writer-wins writes (apply-twice ==
    # apply-once: wholesale row replace, set-insert, or no-op re-apply)
    "register_node": RETRY_AFTER_APPLY,   # replaces the row wholesale
    "heartbeat": RETRY_AFTER_APPLY,       # refreshes a timestamp
    "unregister_node": RETRY_AFTER_APPLY,  # second apply sees no row
    "freed_add": RETRY_AFTER_APPLY,       # tombstone set-insert
    "name_actor": RETRY_AFTER_APPLY,      # same (name, id) re-claim ok
    "drop_actor_name": RETRY_AFTER_APPLY,
    "register_actor": RETRY_AFTER_APPLY,
    "register_actor_spec": RETRY_AFTER_APPLY,
    "drop_actor_spec": RETRY_AFTER_APPLY,
    "loc_add": RETRY_AFTER_APPLY,         # set-insert into the directory
    "loc_add_batch": RETRY_AFTER_APPLY,
    "loc_drop": RETRY_AFTER_APPLY,
    "register_fn": RETRY_AFTER_APPLY,     # setdefault: first write wins
    "cancel": RETRY_AFTER_APPLY,          # cancel of finished is a no-op
    "kill_actor": RETRY_AFTER_APPLY,      # kill of dead is a no-op
    "prestart_workers": RETRY_AFTER_APPLY,  # hint; pool is capped
    "register_driver": RETRY_AFTER_APPLY,
    "driver_heartbeat": RETRY_AFTER_APPLY,
    "unregister_driver": RETRY_AFTER_APPLY,
    "owner_cleanup": RETRY_AFTER_APPLY,   # reclaim of reclaimed: no-op
    # ------------- exactly-once via server-side nonce dedup (_dedup)
    "submit": dedup_keyed("nonce"),
    "actor_call": dedup_keyed("nonce"),   # + MSG_/REQ_ACTOR_CALL
    "create_actor": dedup_keyed("nonce"),  # + MSG_CREATE_ACTOR
    # ------------------------------------------- per-sub-op (msg[1])
    "kv": PER_SUBOP,                      # + REQ_KV — see below
    # --------------------------------- non-retryable GCS / node ops
    "publish": NON_RETRYABLE,        # re-send = duplicate pubsub event
    "drain_node": NON_RETRYABLE,     # idempotent-in-effect; unswept
    "node_drained": NON_RETRYABLE,   # idempotent-in-effect; unswept
    "free": NON_RETRYABLE,           # double refcount decrement hazard
    "put": NON_RETRYABLE,            # second apply stores a second copy
    "release": NON_RETRYABLE,        # double refcount decrement hazard
    "stream_next": NON_RETRYABLE,    # + REQ_STREAM_NEXT (pipe tag)
    "stream_consumed": NON_RETRYABLE,  # monotonic watermark; unswept
    "evict_actor": NON_RETRYABLE,    # epoch-fenced reap; unswept
    "pg": NON_RETRYABLE,             # + REQ_PG — create/remove mutate
    "netem": NON_RETRYABLE,          # test chaos control plumbing
    "shutdown_node": NON_RETRYABLE,
    "shutdown_gcs": NON_RETRYABLE,
    # ------------- driver<->worker pipe tags (no retry machinery on
    # the pipe: a transport failure is a worker/driver death, never a
    # re-send — NON_RETRYABLE by policy, see the module docstring)
    "reg_fn": NON_RETRYABLE,               # MSG_REGISTER_FN
    "task_batch": NON_RETRYABLE,           # MSG_TASK_BATCH
    "shutdown": NON_RETRYABLE,             # MSG_SHUTDOWN
    "ready": NON_RETRYABLE,                # MSG_READY
    "done": NON_RETRYABLE,                 # MSG_DONE
    "error": NON_RETRYABLE,                # MSG_ERROR
    "actor_ready": NON_RETRYABLE,          # MSG_ACTOR_READY
    "actor_error": NON_RETRYABLE,          # MSG_ACTOR_ERROR
    "stream_yield": NON_RETRYABLE,         # MSG_STREAM_YIELD
    "put_meta": NON_RETRYABLE,             # REQ_PUT_META
    "create_actor_req": NON_RETRYABLE,     # REQ_CREATE_ACTOR
    "get_actor": NON_RETRYABLE,            # REQ_GET_ACTOR (read; inert)
    "pkg": NON_RETRYABLE,                  # REQ_PKG (read; inert)
    "pkg_put": NON_RETRYABLE,              # REQ_PKG_PUT
    "need_space": NON_RETRYABLE,           # REQ_NEED_SPACE (spill)
    "free_objs": NON_RETRYABLE,            # REQ_FREE
    "kill_actor_req": NON_RETRYABLE,       # REQ_KILL_ACTOR
    "stream_credit": NON_RETRYABLE,        # REQ_STREAM_CREDIT
    "pubsub": NON_RETRYABLE,               # REQ_PUBSUB
    "put_meta_async": NON_RETRYABLE,       # REQ_PUT_META_ASYNC
    "submit_async": NON_RETRYABLE,         # REQ_SUBMIT_ASYNC
    "actor_call_async": NON_RETRYABLE,     # REQ_ACTOR_CALL_ASYNC
    "stream_consumed_async": NON_RETRYABLE,  # REQ_STREAM_CONSUMED_ASYNC
    "barrier": NON_RETRYABLE,              # REQ_BARRIER
}

#: kv (msg[0] == "kv") classifies per sub-op (msg[1]): overwrites and
#: deletes are LWW; merge/cas_merge are read-modify-write — a replay
#: double-merges (the netem sweep exercises exactly this split).
KV_SUBOP_CONTRACT: Dict[str, str] = {
    "put": RETRY_AFTER_APPLY,     # overwrite: LWW
    "get": IDEMPOTENT,
    "del": RETRY_AFTER_APPLY,     # second delete is a no-op
    "exists": IDEMPOTENT,
    "keys": IDEMPOTENT,
    "merge": NON_RETRYABLE,       # dict.update RMW: replay double-merges
    "cas_merge": NON_RETRYABLE,   # compare-and-swap RMW
}

#: The derived transport whitelist (imported by rpc.py). Pinned to the
#: historical ``_IDEMPOTENT_OPS`` literal by the netem parity test.
RETRY_SAFE_OPS = frozenset(
    op for op, c in WIRE_CONTRACT.items() if retry_safe(c))
RETRY_SAFE_KV_SUBOPS = frozenset(
    sub for sub, c in KV_SUBOP_CONTRACT.items() if retry_safe(c))


# -------------------------------------------------- durability / resync
#
# For every op the GCS write-ahead-logs (gcs.py _WAL_OPS), how does a
# node or driver RE-ACQUIRE that state when the head restarts EMPTY (no
# persist dir, or a wiped one)? L10 statically checks each declaration
# against the code it names:
#
# - "resync:<op>"      the op (or the batch op superseding it) is
#                      re-published by ha.py resync_node — the literal
#                      must appear in resync_node's body.
# - "helper:<fn>"      resync_node re-publishes it through node_server's
#                      <fn>() message builder — resync_node must call
#                      <fn> and <fn>'s body must contain the op literal.
# - "cursor:<key>"     consumers recover through a gcs_info cursor clamp
#                      (<key> must be a key in _op_gcs_info's reply) —
#                      the event stream is re-cut at the head's
#                      watermark rather than re-pushed.
# - "durable"          snapshot+WAL is the ONLY copy (the data has no
#                      second home on a node to re-push from); an EMPTY
#                      restart legitimately loses it. Keep this list
#                      short and justified.
RESYNC_COVERAGE: Dict[str, str] = {
    "register_node": "helper:register_msg",  # node re-registers itself
    "unregister_node": "cursor:death_seq",   # deaths re-cut at watermark
    "kv": "resync:kv",               # node PG slice re-published; other
                                     # kv content is driver-origin and
                                     # durable-only past driver exit
    "name_actor": "resync:name_actor",
    "drop_actor_name": "durable",    # a dropped name needs no re-drop:
                                     # an empty head has no row to drop
    "register_actor": "resync:register_actor",
    "register_actor_spec": "durable",  # restart authority: once handed
                                       # to the GCS the spec's only home
                                       # is snapshot+WAL (driver may be
                                       # long gone)
    "drop_actor_spec": "durable",    # tombstone of a durable row
    "loc_add": "resync:loc_add_batch",   # superseded by the batch op
    "loc_add_batch": "resync:loc_add_batch",
    "loc_drop": "cursor:channel_seq",    # drops re-derive from the freed
                                         # channel replay + fetch misses
    "freed_add": "cursor:channel_seq",   # freed channel re-cut + replay
    "publish": "cursor:channel_seq",     # subscribers clamp + resync
                                         # through the seq-gap path
    "register_fn": "durable",        # re-shipped lazily on first use
                                     # (submit carries pickled_fn)
    "drain_node": "durable",         # operator intent: lives only here;
                                     # restore re-arms the grace window
    "node_drained": "durable",       # terminal lifecycle edge of ^
}
