"""Worker log capture + streaming to the driver.

Reference: python/ray/_private/log_monitor.py — there, a per-node monitor
process tails ``session/logs/worker-*.out|err`` and publishes records over
GCS pubsub; the driver prints them with ``(pid=..., ip=...)`` prefixes.
Here the monitor is a daemon thread inside the driver runtime (and inside
each NodeServer) tailing the session's log directory; remote logs are
served through the node RPC plane (state API ``get_log``).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, Optional, TextIO


def worker_log_paths(log_dir: str, worker_id_hex: str):
    short = worker_id_hex[:8]
    return (os.path.join(log_dir, f"worker-{short}.out"),
            os.path.join(log_dir, f"worker-{short}.err"))


class LogMonitor:
    """Tails every ``worker-*.out|err`` file in ``log_dir`` and forwards
    new lines to ``sink`` (driver stderr by default) with a
    ``(worker=<id> <stream>)`` prefix."""

    def __init__(self, log_dir: str, sink: Optional[TextIO] = None,
                 interval_s: float = 0.2, prefix_node: str = ""):
        self._log_dir = log_dir
        self._sink = sink
        self._interval = interval_s
        self._prefix_node = prefix_node
        self._offsets: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._partial: Dict[str, bytes] = {}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "LogMonitor":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rtpu-log-monitor")
        self._thread.start()
        return self

    def stop(self, flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if flush:
            self.poll_once()
            # a crashed worker's final write may lack the newline — force
            # the stashed partials out so nothing is silently dropped
            for name in list(self._partial):
                self._emit(name, b"\n")

    # -- tailing -----------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.poll_once()
            # rtpu-lint: disable=L4 — crash-proof daemon loop: losing the
            # log monitor silently drops all worker output for the rest
            # of the session; whatever one poll hit, the next one retries
            except Exception:  # noqa: BLE001 — never kill the monitor
                pass

    def poll_once(self) -> None:
        """One scan over the log dir; forwards any appended lines."""
        try:
            names = sorted(os.listdir(self._log_dir))
        except OSError:
            return
        for name in names:
            if not (name.startswith("worker-")
                    and name.endswith((".out", ".err"))):
                continue
            path = os.path.join(self._log_dir, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            off = self._offsets.get(name, 0)
            if size <= off:
                continue
            try:
                # binary mode: offsets are byte positions; text-mode reads
                # count characters and would duplicate/garble multibyte
                # output appended concurrently
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read(size - off)
            except OSError:
                continue
            self._offsets[name] = off + len(chunk)
            self._emit(name, chunk)

    def _emit(self, name: str, chunk: bytes) -> None:
        sink = self._sink if self._sink is not None else sys.stderr
        # worker-<id8>.out -> (worker=<id8> out)
        stem, _, kind = name.rpartition(".")
        wid = stem[len("worker-"):]
        data = self._partial.pop(name, b"") + chunk
        lines = data.split(b"\n")
        # keep an unterminated tail for the next poll
        if lines and lines[-1]:
            self._partial[name] = lines[-1]
        node = f" node={self._prefix_node}" if self._prefix_node else ""
        for line in lines[:-1]:
            try:
                text = line.decode("utf-8", errors="replace")
                sink.write(f"(worker={wid}{node} {kind}) {text}\n")
            except (OSError, ValueError):
                return  # sink closed (interpreter teardown) — stop emitting
        try:
            sink.flush()
        except (OSError, ValueError):
            pass


def list_log_files(log_dir: str):
    """Names + sizes of session log files (state API ``list_logs``)."""
    out = []
    try:
        for name in sorted(os.listdir(log_dir)):
            p = os.path.join(log_dir, name)
            if os.path.isfile(p):
                out.append({"name": name, "size": os.path.getsize(p)})
    except OSError:
        pass
    return out


def read_log_file(log_dir: str, name: str, tail_lines: int = 1000) -> str:
    """Last ``tail_lines`` of one session log file (state API
    ``get_log``). ``name`` must be a bare filename inside the log dir."""
    if os.sep in name or name.startswith("."):
        raise ValueError(f"invalid log name {name!r}")
    path = os.path.join(log_dir, name)
    from collections import deque

    with open(path, "r", errors="replace") as f:
        # bounded memory: keep only the last tail_lines while scanning
        lines = deque(f, maxlen=tail_lines)
    return "".join(lines)
