"""Env rules shared by BOTH worker spawn paths (cold Popen and zygote
fork). One definition so a new TPU/PJRT env rule can never apply to one
path and silently miss the other."""

from __future__ import annotations


def sanitize_cpu_worker_env(env) -> None:
    """Strip TPU/PJRT triggers from a plain CPU pool worker's env.

    This environment's sitecustomize keys TPU plugin registration (and a
    ~2s jax import) off these variables; CPU workers must never pay that
    or claim the chip. Mutates ``env`` in place (works for both a dict
    and os.environ)."""
    env.pop("PALLAS_AXON_POOL_IPS", None)
    if env.get("JAX_PLATFORMS", "axon") == "axon":
        env["JAX_PLATFORMS"] = "cpu"
