"""Serialization for task args/returns and ``put`` objects.

Pickle protocol 5 with out-of-band buffers (the reference uses the same
approach via cloudpickle: python/ray/_private/serialization.py). Large buffer
payloads (numpy arrays, jax host arrays, bytes) are written to the
shared-memory object store and mapped zero-copy on read; small objects are
inlined into control messages (reference inlines <100KB task returns into the
in-process memory store).

Wire container format (used both inline and inside a shm object)::

    u32  magic        (0x52545055 'RTPU')
    u32  num_buffers
    u64  pickle_len
    u64  buffer_len[num_buffers]
    ...  pickled bytes
    ...  buffers, each 64-byte aligned
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle

_MAGIC = 0x52545055
_ALIGN = 64
# Objects whose serialized size is below this are inlined into control-plane
# messages instead of the shm store (reference: 100KB task-return inline cap).
from ray_tpu.core.config import config as _config


def inline_threshold() -> int:
    """Size cutoff below which values travel inline rather than via shm.
    Read per-call so config.reload() takes effect (flag:
    inline_threshold_bytes)."""
    return _config.inline_threshold_bytes


def _align(offset: int) -> int:
    return (offset + _ALIGN - 1) & ~(_ALIGN - 1)


def serialize(obj: Any) -> Tuple[bytes, List[memoryview], int]:
    """Serialize ``obj``.

    Returns (pickled_bytes, oob_buffers, total_container_size).
    """
    buffers: List[pickle.PickleBuffer] = []
    pickled = cloudpickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    views = [b.raw() for b in buffers]
    header = 16 + 8 * len(views)
    total = _align(header + len(pickled))
    for v in views:
        total = _align(total + v.nbytes)
    return pickled, views, total


def write_container(dst: memoryview, pickled: bytes, views: List[memoryview]) -> int:
    """Write the container format into ``dst``; returns bytes written."""
    struct.pack_into("<IIQ", dst, 0, _MAGIC, len(views), len(pickled))
    off = 16
    for v in views:
        struct.pack_into("<Q", dst, off, v.nbytes)
        off += 8
    dst[off : off + len(pickled)] = pickled
    off = _align(off + len(pickled))
    for v in views:
        flat = v.cast("B") if v.ndim != 1 or v.format != "B" else v
        if flat.nbytes >= (1 << 20):
            # np.copyto streams ~2x faster than memoryview slice assignment
            # for multi-MB copies (vectorized non-temporal stores).
            import numpy as _np

            _np.copyto(
                _np.frombuffer(dst[off : off + flat.nbytes], dtype=_np.uint8),
                _np.frombuffer(flat, dtype=_np.uint8),
            )
        else:
            dst[off : off + flat.nbytes] = flat
        off = _align(off + flat.nbytes)
    return off


def pack(obj: Any) -> bytes:
    """Serialize to a standalone bytes container (for inline transport)."""
    pickled, views, total = serialize(obj)
    out = bytearray(total)
    write_container(memoryview(out), pickled, views)
    return bytes(out)


def unpack(data, wrap_buffer=None) -> Any:
    """Deserialize a container from bytes/memoryview.

    When ``data`` is a memoryview over shared memory, buffers are zero-copy
    views into it. ``wrap_buffer(mv_slice)`` lets the caller substitute a
    lifetime-tracked buffer object (used by the shm store to pin objects for
    as long as deserialized arrays reference them).
    """
    mv = memoryview(data)
    magic, num_buffers, pickle_len = struct.unpack_from("<IIQ", mv, 0)
    if magic != _MAGIC:
        raise ValueError("corrupt object container (bad magic)")
    off = 16
    buf_lens = []
    for _ in range(num_buffers):
        (n,) = struct.unpack_from("<Q", mv, off)
        buf_lens.append(n)
        off += 8
    pickled = bytes(mv[off : off + pickle_len])
    off = _align(off + pickle_len)
    buffers = []
    for n in buf_lens:
        chunk = mv[off : off + n]
        buffers.append(wrap_buffer(chunk) if wrap_buffer is not None else chunk)
        off = _align(off + n)
    return pickle.loads(pickled, buffers=buffers)
