"""Process-local runtime context: which core client this process uses.

Driver processes install a :class:`ray_tpu.core.runtime.Runtime`; worker
processes install a :class:`ray_tpu.core.worker_main.WorkerCore`. Both expose
the same core-client surface (submit_task/get_objects/put_object/...), the
analogue of the reference's per-process ``CoreWorker``
(src/ray/core_worker/core_worker.h:295).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.exceptions import RuntimeNotInitializedError

_core = None


def set_core(core) -> None:
    global _core
    _core = core


def get_core():
    if _core is None:
        raise RuntimeNotInitializedError(
            "ray_tpu is not initialized; call ray_tpu.init() first."
        )
    return _core


def get_core_or_none():
    return _core


def is_initialized() -> bool:
    return _core is not None


class RuntimeContext:
    """User-visible context (reference: python/ray/runtime_context.py)."""

    @property
    def initialized(self) -> bool:
        return is_initialized()

    def get_node_id(self) -> Optional[str]:
        core = get_core_or_none()
        return core.node_id.hex() if core is not None else None

    def get_worker_id(self) -> Optional[str]:
        core = get_core_or_none()
        return core.worker_id.hex() if core is not None else None

    def get_actor_id(self) -> Optional[str]:
        core = get_core_or_none()
        aid = getattr(core, "current_actor_id", None)
        return aid.hex() if aid is not None else None

    def get_task_id(self) -> Optional[str]:
        core = get_core_or_none()
        tid = getattr(core, "current_task_id", None)
        return tid.hex() if tid is not None else None


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()
