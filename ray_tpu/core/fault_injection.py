"""Deterministic, targetable fault injection for chaos tests.

The probabilistic chaos knobs (``testing_kill_worker_prob``,
``testing_rpc_delay_ms``) exercise failure paths statistically; tests of
specific recovery machinery (lineage reconstruction, spill-file loss,
worker death mid-task) need each loss to happen to a *chosen* object at
a *named* site, an exact number of times. This module provides that:
product code calls ``fire(site, key)`` at instrumented sites and applies
the returned action; tests arm faults with ``inject`` (in-process) or
via the env/config surface (cross-process).

Sites and their actions (``key`` is the hex id the match is tested
against, prefix match; ``"*"`` matches everything):

=============  =======================  ==================================
site           key                      actions
=============  =======================  ==================================
``get``        object id (hex)          ``evict``, ``delete_spill``,
                                        ``corrupt_spill`` — applied to the
                                        object just before a driver-side
                                        get decodes it
``spill``      object id (hex)          ``delete``, ``corrupt`` — applied
                                        to the spill file right after the
                                        payload moved to disk
``dispatch``   function id (hex)        ``kill_worker`` — SIGKILL the
                                        worker a task batch was just sent
                                        to
``task``       function id (hex)        ``exit`` — the worker process
                                        exits before executing the task
                                        (worker-side; arm via env)
``actor_call``  "<actor hex>:<method>"  ``drop`` — the driver silently
                                        drops the dispatch (the call is
                                        in flight but the worker never
                                        sees it — a lost message);
                                        ``kill_worker`` — SIGKILL the
                                        actor's worker right after the
                                        call is sent
``actor_worker_kill``  same key         ``exit`` — the actor's worker
                                        exits before executing the call
                                        (in-flight kill); ``exit_after``
                                        — it executes the method and
                                        seals the results, then exits
                                        before the DONE report flushes
                                        (worker-side; arm via env)
``gcs_kill``   GCS op name              ``kill`` — SIGKILL the GCS
                                        process as it starts handling a
                                        matching op, before the op is
                                        applied or WAL'd (head-node
                                        chaos; arm via env — the site
                                        fires inside the GCS process)
``gang_resize``  batch index (decimal)  ``kill`` — SIGKILL the
                                        highest-rank training worker
                                        right after the matching result
                                        batch is harvested (abrupt
                                        preemption); ``sigterm`` —
                                        deliver SIGTERM instead, giving
                                        the worker its checkpoint grace
                                        window (scheduled preemption).
                                        Fires driver-side inside
                                        BackendExecutor, so in-process
                                        ``inject`` works
``serve_overload``  deployment name     ``shed`` — the serve router's
                                        admission check rejects the
                                        matching request with
                                        BackpressureError as if the
                                        deployment were saturated
                                        (deterministic overload: the
                                        typed-shed path fires without
                                        needing real queue pressure).
                                        Fires in the router (driver or
                                        proxy process), so in-process
                                        ``inject`` works
``prefill_handoff``  request id         ``drop`` — the finished KV-page
                                        handoff from a disaggregated
                                        prefill worker to its decode
                                        engine is silently lost (pages
                                        computed, message never
                                        delivered); the decode side's
                                        handoff lease expires and the
                                        request re-prefills locally.
                                        ``kill_worker`` — the prefill
                                        worker aborts mid-stream before
                                        publishing anything (worker
                                        death); a fresh worker is
                                        respawned and the request
                                        recovers the same way. Fires on
                                        the worker thread inside the
                                        replica process, so in-process
                                        ``inject`` works
``job_claim``  job id                   ``drop`` — the job agent
                                        abandons a claim right after the
                                        PENDING -> RUNNING cas succeeds,
                                        without spawning the entrypoint
                                        (an agent that died mid-claim);
                                        the lease-expiry orphan detector
                                        must recover the job. Fires in
                                        the agent's process, so
                                        in-process ``inject`` works
``serve_replica_kill``  "<deployment>:<replica id>"  ``die`` — the serve
                                        router observes a synthetic
                                        ActorDiedError for the replica
                                        it just picked BEFORE the call
                                        dispatches (a lost request: the
                                        replay must re-pick and re-
                                        execute); ``die_after`` — the
                                        call executes on the replica,
                                        then the router discards the
                                        result and observes the death
                                        (a lost reply: the replay must
                                        be absorbed by replica-side
                                        nonce dedup for exactly-once).
                                        Fires in the router's process,
                                        so in-process ``inject`` works
``stream_resume``  deployment name      ``drop`` — an engine token
                                        stream observes replica death
                                        right after delivering its next
                                        chunk, forcing the mid-stream
                                        resume path (serve_request_
                                        replay): re-pick, resubmit
                                        prompt + delivered tokens,
                                        splice at the watermark. Fires
                                        in the router's process, so
                                        in-process ``inject`` works
=============  =======================  ==================================

Env/config surface: ``RTPU_FAULT_<SITE>=<action>[:<times>[:<match>]]``
(e.g. ``RTPU_FAULT_SPILL=delete:1``), or the ``fault_injection`` config
flag as comma-separated ``<site>=<action>[:<times>[:<match>]]`` specs.
``times`` defaults to 1; ``-1`` means unlimited. Workers inherit the
driver's environment, so env-armed faults fire in every process that
hits the site; in-process ``inject`` calls arm only the calling process.

The module also exposes direct helpers (``evict_object``,
``spill_object``, ``delete_spill_file``, ``corrupt_spill_file``,
``kill_producing_worker``) that apply a fault to a runtime immediately —
for tests that want to mutate state between calls rather than arm a
site.

This module covers *application-level* sites (a named operation loses
its object / worker / process). WIRE-level faults — partitions, drops,
delays, duplicate deliveries, bandwidth caps on a chosen network edge —
live in :mod:`ray_tpu.core.netem`, armed via the sibling ``RTPU_NETEM``
env protocol with the same seeded-determinism contract.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Dict, List, Optional

from ray_tpu.util.debug_lock import make_lock

SITES = ("get", "spill", "dispatch", "task", "actor_call",
         "actor_worker_kill", "gcs_kill", "gang_resize", "serve_overload",
         "job_claim", "prefill_handoff", "serve_replica_kill",
         "stream_resume")

_lock = make_lock("fault_injection._lock")
_specs: Dict[str, List[dict]] = {}
_armed = False


def enabled() -> bool:
    """Cheap guard for instrumented hot paths."""
    return _armed


def inject(site: str, action: str, target: str = "*",
           times: int = 1) -> None:
    """Arm ``action`` at ``site`` for keys matching ``target`` (hex
    prefix or ``"*"``), firing at most ``times`` times (-1 = always)."""
    if site not in SITES:
        raise ValueError(f"unknown fault site {site!r}; sites: {SITES}")
    global _armed
    with _lock:
        _specs.setdefault(site, []).append(
            {"action": action, "target": target, "times": times})
        _armed = True


def fire(site: str, key: str) -> Optional[str]:
    """Called by product code at an instrumented site. Returns the armed
    action to apply for ``key`` (consuming one firing), or None."""
    if not _armed:
        return None
    with _lock:
        for spec in _specs.get(site, ()):
            if spec["times"] == 0:
                continue
            t = spec["target"]
            if t != "*" and not key.startswith(t):
                continue
            if spec["times"] > 0:
                spec["times"] -= 1
            return spec["action"]
    return None


def clear() -> None:
    """Disarm every fault (in-process specs AND env-loaded ones)."""
    global _armed
    with _lock:
        _specs.clear()
        _armed = False


def _parse_spec(site: str, raw: str) -> Optional[dict]:
    parts = raw.split(":")
    if not parts[0]:
        return None
    action = parts[0].strip()
    times = int(parts[1]) if len(parts) > 1 and parts[1].strip() else 1
    target = parts[2].strip() if len(parts) > 2 and parts[2].strip() else "*"
    return {"action": action, "target": target, "times": times,
            "site": site}


def load_env(env: Optional[Dict[str, str]] = None) -> int:
    """(Re-)arm faults from RTPU_FAULT_<SITE> env vars and the
    ``fault_injection`` config flag. Returns the number of specs armed.
    Called once at import; tests that mutate os.environ call it again."""
    from ray_tpu.core.config import config

    env = os.environ if env is None else env
    specs: List[dict] = []
    for site in SITES:
        raw = env.get(f"RTPU_FAULT_{site.upper()}")
        if raw:
            s = _parse_spec(site, raw)
            if s:
                specs.append(s)
    for item in (config.fault_injection or "").split(","):
        item = item.strip()
        if not item or "=" not in item:
            continue
        site, _, raw = item.partition("=")
        site = site.strip()
        if site in SITES:
            s = _parse_spec(site, raw)
            if s:
                specs.append(s)
    global _armed
    with _lock:
        # env-loaded specs replace prior env-loaded specs but keep
        # inject()-armed ones
        for lst in _specs.values():
            lst[:] = [s for s in lst if not s.get("env")]
        for s in specs:
            s["env"] = True
            _specs.setdefault(s.pop("site"), []).append(s)
        _armed = any(lst for lst in _specs.values())
    return len(specs)


# ---------------------------------------------------------------- helpers
# Direct-application forms of the site actions: each takes the Runtime
# (`core`) and an object ref / ObjectID / raw id bytes, applies the
# fault now, and returns whether it took effect.


def _oid_bytes(ref) -> bytes:
    if isinstance(ref, bytes):
        return ref
    if hasattr(ref, "id"):  # ObjectRef
        return ref.id.binary()
    return ref.binary()  # ObjectID


def evict_object(core, ref, timeout_s: float = 2.0) -> bool:
    """Evict a sealed object's shm container exactly as LRU pressure
    would: drop the owner's tracking pin and delete the container. The
    object-table entry keeps its stale ("shm", id) payload, so the next
    read surfaces ObjectLostError (or triggers reconstruction).

    Retries through the result-adoption handoff: _store_payload sets
    the entry event before the pin registration runs, and inside that
    window the container still holds its retained creator ref, so
    delete refuses. A getter woken by the event (or the interleaving
    fuzzer stretching the window) would otherwise see the injected
    loss silently no-op."""
    import time

    from ray_tpu.core.ids import ObjectID

    oid_b = _oid_bytes(ref)
    oid = ObjectID(oid_b)
    deadline = time.monotonic() + timeout_s
    while True:
        with core._spill_lock:
            pinned = core._pinned.pop(oid_b, None) is not None
        try:
            if pinned:
                core.store.release(oid)
            core.store.delete(oid)
        # rtpu-lint: disable=L4 — chaos helper: the object being already
        # evicted/spilled/closed-with-the-store all count as "gone",
        # which is the success condition checked below
        except Exception:  # noqa: BLE001
            pass
        if not core.store.contains(oid):
            return True
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.001)


def spill_object(core, ref) -> bool:
    """Force an object's container to disk now (deterministic stand-in
    for memory pressure). Returns True when the payload moved."""
    return core._spill_one(_oid_bytes(ref)) > 0


def _spill_path(core, ref) -> Optional[str]:
    from ray_tpu.core.ids import ObjectID

    e = core._objects.get(ObjectID(_oid_bytes(ref)))
    if e is None or e.payload is None:
        return None
    kind, data = e.payload
    if kind != "spilled":
        return None
    return data[0] if isinstance(data, tuple) else data


def delete_spill_file(core, ref) -> bool:
    """Delete the spill file backing an already-spilled object."""
    from ray_tpu.core import external_storage

    path = _spill_path(core, ref)
    if path is None:
        return False
    external_storage.delete(path)
    return True


def corrupt_spill_file(core, ref) -> bool:
    """Overwrite the head of an object's spill file with garbage."""
    from ray_tpu.core import external_storage

    path = _spill_path(core, ref)
    if path is None:
        return False
    return external_storage.corrupt(path)


def kill_producing_worker(core, ref) -> bool:
    """SIGKILL the worker currently executing the task that produces
    ``ref`` (keyed by the task's first return id)."""
    oid_b = _oid_bytes(ref)
    spec = core._cancellable.get(oid_b)
    if spec is None:
        return False
    tid_b = spec.task_id.binary()
    with core._lock:
        procs = [w.proc for w in core._workers.values()
                 if tid_b in w.inflight and w.proc is not None]
    for proc in procs:
        try:
            os.kill(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
    return bool(procs)


load_env()
