"""Actor API (reference: python/ray/actor.py — ActorClass :566, ActorHandle :1223).

An actor is a stateful worker: ``@ray_tpu.remote`` on a class gives an
``ActorClass``; ``.remote(...)`` instantiates it in a dedicated worker
process; method calls are submitted in order and return ObjectRefs.
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, List, Optional, Union

from ray_tpu.core.ids import ActorID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core import runtime_context


class ActorMethod:
    """Bound method accessor: ``handle.method.remote(args)``."""

    def __init__(self, handle: "ActorHandle", name: str, num_returns=1,
                 max_task_retries=None, retry_exceptions=None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        # None = inherit the class-level default (resolved runtime-side
        # from the actor's opts); reference: max_task_retries /
        # retry_exceptions on ray.method (python/ray/actor.py:566)
        self._max_task_retries = max_task_retries
        self._retry_exceptions = retry_exceptions

    def options(self, num_returns=None, max_task_retries=None,
                retry_exceptions=None):
        """Per-call overrides. Unknown keyword arguments raise TypeError
        (a typo like ``max_retires=`` must not pass silently); ``None``
        keeps the method's current setting."""
        return ActorMethod(
            self._handle, self._name,
            self._num_returns if num_returns is None else num_returns,
            (self._max_task_retries if max_task_retries is None
             else max_task_retries),
            (self._retry_exceptions if retry_exceptions is None
             else retry_exceptions),
        )

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        core = runtime_context.get_core()
        call_opts = {}
        if self._max_task_retries is not None:
            call_opts["max_task_retries"] = self._max_task_retries
        if self._retry_exceptions is not None:
            call_opts["retry_exceptions"] = self._retry_exceptions
        refs = core.submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs,
            num_returns=self._num_returns, options=call_opts or None,
        )
        if self._num_returns == "streaming":
            from ray_tpu.core.remote_function import _make_generator

            return _make_generator(core, refs[0].binary())
        return refs[0] if self._num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._name!r} cannot be called directly; "
            f"use .{self._name}.remote()."
        )


class ActorHandle:
    """Serializable reference to a live actor."""

    def __init__(self, actor_id: ActorID, method_opts: Optional[dict] = None):
        self._actor_id = actor_id
        self._method_opts = method_opts or {}

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        opts = self._method_opts.get(name, {})
        return ActorMethod(self, name,
                           num_returns=opts.get("num_returns", 1),
                           max_task_retries=opts.get("max_task_retries"),
                           retry_exceptions=opts.get("retry_exceptions"))

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_opts))

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:12]})"


class ActorClass:
    """A class decorated with ``@ray_tpu.remote``."""

    def __init__(self, cls, default_options: Optional[dict] = None):
        self._cls = cls
        self._default_options = dict(default_options or {})
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__!r} cannot be instantiated "
            f"directly; use {self._cls.__name__}.remote()."
        )

    def options(self, **opts) -> "_ActorOptionWrapper":
        merged = dict(self._default_options)
        merged.update(opts)
        return _ActorOptionWrapper(self, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._default_options)

    def _remote(self, args, kwargs, options) -> ActorHandle:
        core = runtime_context.get_core()
        opts = dict(options)
        opts["has_async_methods"] = any(
            inspect.iscoroutinefunction(m)
            for _, m in inspect.getmembers(self._cls, inspect.isfunction)
        )
        # Collect per-method options set via @ray_tpu.method(...) so that
        # handles (including deserialized ones) know e.g. num_returns.
        method_opts = {
            name: m.__rtpu_method_opts__
            for name, m in inspect.getmembers(self._cls, inspect.isfunction)
            if getattr(m, "__rtpu_method_opts__", None)
        }
        opts["method_opts"] = method_opts
        if opts.get("runtime_env") and hasattr(core, "prepare_runtime_env"):
            # package working_dir/py_modules paths into hash references
            opts["runtime_env"] = core.prepare_runtime_env(
                opts["runtime_env"])
        if hasattr(core, "register_function"):
            cls_fn_id = core.register_function(self._cls)
            actor_id = core.create_actor(cls_fn_id, args, kwargs, opts)
        else:
            # worker path: ship the pickled class on first use
            from ray_tpu.core import serialization
            import hashlib

            pickled = serialization.pack(self._cls)
            fn_id = hashlib.blake2b(pickled, digest_size=16).digest()
            actor_id = core.create_actor_from_worker(
                fn_id, pickled, args, kwargs, opts)
        return ActorHandle(actor_id, method_opts)

    @property
    def underlying_class(self):
        return self._cls

    def __reduce__(self):
        return (_rebuild_actor_class, (self._cls, self._default_options))


def _rebuild_actor_class(cls, default_options):
    return ActorClass(cls, default_options)


class _ActorOptionWrapper:
    def __init__(self, ac: ActorClass, options: dict):
        self._ac = ac
        self._options = options

    def remote(self, *args, **kwargs):
        return self._ac._remote(args, kwargs, self._options)


def get_actor(name: str) -> ActorHandle:
    """Look up a named actor (reference: ray.get_actor, worker.py:2904)."""
    core = runtime_context.get_core()
    if hasattr(core, "get_named_actor"):
        aid = core.get_named_actor(name)
        return ActorHandle(aid, core.get_actor_method_opts(aid))
    return core.get_actor_handle(name)
