"""Node memory monitor + OOM worker-killing policy.

Reference: src/ray/common/memory_monitor.h:52 (threshold polling of
cgroup/host memory) and raylet/worker_killing_policy_group_by_owner.h
(group tasks by owner, kill the newest retriable task first, retries
don't consume the task's budget).

Two accounting modes:
- host (default): usage fraction of cgroup v2 limit when present, else
  /proc/meminfo (1 - MemAvailable/MemTotal). This is what production
  nodes run.
- bounded: ``RTPU_MEMORY_LIMIT_BYTES`` > 0 caps the WORKER TREE's
  summed RSS. Deterministic for tests and useful to fence the framework
  off from co-tenant processes on shared TPU-VM hosts.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

_PAGE = os.sysconf("SC_PAGE_SIZE")


def cgroup_memory() -> Optional[Tuple[int, int]]:
    """(used, limit) from cgroup v2, or None when unlimited/absent."""
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            raw = f.read().strip()
        if raw == "max":
            return None
        limit = int(raw)
        with open("/sys/fs/cgroup/memory.current") as f:
            used = int(f.read().strip())
        return used, limit
    except (OSError, ValueError):
        return None


def host_memory() -> Tuple[int, int]:
    """(used, total) from /proc/meminfo (available-based, like the
    reference's MemoryMonitor::GetLinuxMemoryBytes)."""
    total = avail = 0
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemTotal:"):
                total = int(line.split()[1]) * 1024
            elif line.startswith("MemAvailable:"):
                avail = int(line.split()[1]) * 1024
            if total and avail:
                break
    return total - avail, total


def process_rss(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return 0


def _descendants(roots: List[int]) -> List[int]:
    """roots + every live descendant, via one /proc scan (tasks may fork
    helpers — multiprocessing pools, DataLoader workers — whose memory
    must count against the tree bound)."""
    children: dict = {}
    try:
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            try:
                with open(f"/proc/{entry}/stat") as f:
                    ppid = int(f.read().rsplit(")", 1)[1].split()[1])
            except (OSError, ValueError, IndexError):
                continue
            children.setdefault(ppid, []).append(int(entry))
    except OSError:
        return list(roots)
    out, queue = [], list(roots)
    seen = set()
    while queue:
        pid = queue.pop()
        if pid in seen:
            continue
        seen.add(pid)
        out.append(pid)
        queue.extend(children.get(pid, ()))
    return out


def tree_rss(pids: List[int]) -> int:
    return sum(process_rss(p) for p in _descendants(pids))


class MemoryMonitor:
    """Computes the current memory-usage fraction for the kill policy."""

    def __init__(self, limit_bytes: int = 0):
        self.limit_bytes = limit_bytes  # 0 -> host mode

    def usage_fraction(self, worker_pids: List[int]) -> float:
        if self.limit_bytes > 0:
            return tree_rss(worker_pids) / self.limit_bytes
        cg = cgroup_memory()
        if cg is not None:
            used, limit = cg
            return used / max(1, limit)
        used, total = host_memory()
        return used / max(1, total)
