"""Driver-side runtime: object directory, worker pool, and task scheduler.

Single-node analogue of the reference's driver CoreWorker + raylet + GCS
rolled into the driver process (the multi-node split arrives with the cluster
control plane):

- Object directory + memory store: the ownership table. The driver owns every
  object; small values live inline here, large values in the shm store
  (reference: src/ray/core_worker/store_provider/memory_store/memory_store.h,
  reference ownership model: src/ray/core_worker/reference_count.h:61).
- Worker pool: forks/pools worker processes, tracks idle/busy, restarts
  actors (reference: src/ray/raylet/worker_pool.h:153).
- Scheduler: FIFO dispatch of ready tasks (deps resolved) onto idle workers;
  per-actor ordered queues (reference: raylet local_task_manager.cc dispatch
  loop + actor_task_submitter.h ordering).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from collections import deque
from multiprocessing.connection import Listener
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.core import protocol, serialization
from ray_tpu.core.ids import (
    ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID, make_task_id,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core import runtime_context
from ray_tpu.core.object_store.store import ShmObjectStore, default_store_capacity
from ray_tpu.core.protocol import _TopLevelDep
from ray_tpu.exceptions import (
    ActorDiedError, GetTimeoutError, TaskError, WorkerCrashedError,
)


class _ObjectEntry:
    __slots__ = ("event", "payload", "callbacks")

    def __init__(self):
        self.event = threading.Event()
        self.payload = None  # protocol.Payload once ready
        self.callbacks: List[Callable[[], None]] = []


class _TaskSpec:
    __slots__ = (
        "task_id", "fn_id", "args_payload", "deps", "return_ids", "options",
        "actor_id", "method", "pending_deps",
    )

    def __init__(self, task_id, fn_id, args_payload, deps, return_ids, options,
                 actor_id=None, method=None):
        self.task_id = task_id
        self.fn_id = fn_id
        self.args_payload = args_payload
        self.deps = deps
        self.return_ids = return_ids
        self.options = options
        self.actor_id = actor_id
        self.method = method
        self.pending_deps = 0


class _Worker:
    __slots__ = (
        "worker_id", "proc", "task_conn", "data_conn", "ready", "alive",
        "registered_fns", "actor_id", "inflight", "reader", "data_thread",
        "send_lock", "blocked",
    )

    def __init__(self, worker_id, proc):
        self.worker_id = worker_id
        self.proc = proc
        self.task_conn = None
        self.data_conn = None
        self.ready = False
        self.alive = True
        self.registered_fns = set()
        self.actor_id: Optional[ActorID] = None
        self.inflight: Optional[_TaskSpec] = None
        self.reader: Optional[threading.Thread] = None
        self.data_thread: Optional[threading.Thread] = None
        # Connection.send is not thread-safe; every task_conn.send goes
        # through this lock (reader thread, dispatchers, shutdown).
        self.send_lock = threading.Lock()
        # True while the worker is blocked in a driver-side get/wait; used
        # by the scheduler to oversubscribe the pool instead of deadlocking.
        self.blocked = False


class _ActorState:
    __slots__ = (
        "actor_id", "worker", "cls_fn_id", "creation_args_payload",
        "creation_deps", "opts", "queue", "ready", "dead", "death_cause",
        "restarts_left", "name", "creation_event",
    )

    def __init__(self, actor_id, cls_fn_id, args_payload, deps, opts):
        self.actor_id = actor_id
        self.worker: Optional[_Worker] = None
        self.cls_fn_id = cls_fn_id
        self.creation_args_payload = args_payload
        self.creation_deps = deps
        self.opts = opts
        self.queue: deque = deque()
        self.ready = False
        self.dead = False
        self.death_cause: Optional[BaseException] = None
        self.restarts_left = opts.get("max_restarts", 0)
        self.name = opts.get("name")
        self.creation_event = threading.Event()


class Runtime:
    """The driver core client. One per driver process."""

    def __init__(self, num_workers: Optional[int] = None,
                 object_store_memory: Optional[int] = None,
                 session_name: Optional[str] = None):
        self.node_id = NodeID.from_random()
        self.worker_id = WorkerID.from_random()
        self.job_id = JobID.from_random()
        self.num_workers = num_workers or max(2, (os.cpu_count() or 4))
        self._session = session_name or f"rtpu_{os.getpid()}_{self.node_id.hex()[:8]}"
        self._sock_path = os.path.join("/tmp", self._session + ".sock")
        self._authkey = os.urandom(16)

        self.store = ShmObjectStore.create(
            "/" + self._session,
            object_store_memory or default_store_capacity(),
        )

        self._lock = threading.Lock()
        self._objects: Dict[ObjectID, _ObjectEntry] = {}
        self._functions: Dict[bytes, bytes] = {}  # fn_id -> pickled
        self._fn_cache: Dict[int, Tuple[bytes, bytes]] = {}  # id(fn) -> (fn_id, pickled)
        self._workers: Dict[WorkerID, _Worker] = {}
        self._idle: deque = deque()
        self._task_queue: deque = deque()
        self._actors: Dict[ActorID, _ActorState] = {}
        self._named_actors: Dict[str, ActorID] = {}
        self._kv: Dict[str, Any] = {}
        self._shutdown = False
        self._spawning = 0

        self._listener = Listener(self._sock_path, family="AF_UNIX",
                                  authkey=self._authkey)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rtpu-accept"
        )
        self._accept_thread.start()
        for _ in range(self.num_workers):
            self._spawn_worker()

    # ------------------------------------------------------------------ pool

    def _spawn_worker(self, tpu: bool = False) -> _Worker:
        worker_id = WorkerID.from_random()
        env = dict(os.environ)
        env.update(
            RTPU_ADDRESS=self._sock_path,
            RTPU_AUTH=self._authkey.hex(),
            RTPU_STORE="/" + self._session,
            RTPU_NODE_ID=self.node_id.hex(),
            RTPU_WORKER_ID=worker_id.hex(),
        )
        if not tpu:
            # Plain pool workers skip TPU/PJRT plugin registration, which
            # this environment's sitecustomize triggers off these vars and
            # which costs ~2s of jax import per process. Workers that land
            # TPU actors (num_tpus>0) are spawned with the env intact.
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env.setdefault("JAX_PLATFORMS", "cpu")
            if env.get("JAX_PLATFORMS") == "axon":
                env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker_main"],
            env=env, stdin=subprocess.DEVNULL,
        )
        w = _Worker(worker_id, proc)
        with self._lock:
            self._workers[worker_id] = w
            self._spawning += 1
        return w

    def _accept_loop(self):
        while not self._shutdown:
            try:
                conn = self._listener.accept()
                hello = conn.recv()
            except (OSError, EOFError, Exception):
                if self._shutdown:
                    return
                continue
            if hello[0] != "hello":
                conn.close()
                continue
            _, kind, wid_bytes = hello
            wid = WorkerID(wid_bytes)
            with self._lock:
                w = self._workers.get(wid)
            if w is None:
                conn.close()
                continue
            if kind == "task":
                w.task_conn = conn
                w.reader = threading.Thread(
                    target=self._worker_reader, args=(w,), daemon=True,
                    name=f"rtpu-read-{wid.hex()[:6]}",
                )
                w.reader.start()
            else:
                w.data_conn = conn
                w.data_thread = threading.Thread(
                    target=self._data_server, args=(w,), daemon=True,
                    name=f"rtpu-data-{wid.hex()[:6]}",
                )
                w.data_thread.start()

    # --------------------------------------------------------- reader threads

    def _worker_reader(self, w: _Worker):
        try:
            while True:
                msg = w.task_conn.recv()
                tag = msg[0]
                if tag == protocol.MSG_READY:
                    with self._lock:
                        w.ready = True
                        self._spawning -= 1
                        # Workers pre-claimed for an actor never join the
                        # general idle pool.
                        if w.actor_id is None:
                            self._idle.append(w)
                    self._dispatch()
                elif tag == protocol.MSG_DONE:
                    self._on_task_done(w, msg[1], msg[2])
                elif tag == protocol.MSG_ERROR:
                    self._on_task_error(w, msg[1], msg[2])
                elif tag == protocol.MSG_ACTOR_READY:
                    self._on_actor_ready(w, ActorID(msg[1]))
                elif tag == protocol.MSG_ACTOR_ERROR:
                    self._on_actor_error(w, ActorID(msg[1]), msg[2])
        except (EOFError, OSError):
            pass
        finally:
            self._on_worker_death(w)

    def _on_worker_death(self, w: _Worker):
        if self._shutdown:
            return
        with self._lock:
            if not w.alive:
                return
            w.alive = False
            self._workers.pop(w.worker_id, None)
            try:
                self._idle.remove(w)
            except ValueError:
                pass
            inflight = w.inflight
            w.inflight = None
            actor_id = w.actor_id
        if inflight is not None:
            err = WorkerCrashedError(
                f"worker {w.worker_id.hex()[:8]} died while executing task"
            )
            self._store_error(inflight.return_ids, err)
        if actor_id is not None:
            self._handle_actor_worker_death(actor_id)
        else:
            # replace pool capacity
            if not self._shutdown:
                self._spawn_worker()
        self._dispatch()

    # ------------------------------------------------------------- functions

    def register_function(self, fn) -> bytes:
        """Pickle a function once; returns its fn_id (content hash).

        The reference exports pickled functions to the GCS function table once
        per job (python/ray/_private/function_manager.py); here the registry
        lives in the driver and is lazily pushed per worker.
        """
        key = id(fn)
        cached = self._fn_cache.get(key)
        if cached is not None and cached[1] is fn:
            return cached[0]
        pickled = serialization.pack(fn)
        import hashlib

        fn_id = hashlib.blake2b(pickled, digest_size=16).digest()
        with self._lock:
            self._functions[fn_id] = pickled
        self._fn_cache[key] = (fn_id, fn)
        return fn_id

    def _send_msg(self, w: _Worker, msg) -> None:
        with w.send_lock:
            w.task_conn.send(msg)

    def _ensure_fn_on_worker(self, w: _Worker, fn_id: bytes):
        if fn_id not in w.registered_fns:
            self._send_msg(
                w, (protocol.MSG_REGISTER_FN, fn_id, self._functions[fn_id])
            )
            w.registered_fns.add(fn_id)

    # ------------------------------------------------------------ object dir

    def _entry(self, oid: ObjectID) -> _ObjectEntry:
        with self._lock:
            e = self._objects.get(oid)
            if e is None:
                e = _ObjectEntry()
                self._objects[oid] = e
            return e

    def _store_payload(self, oid: ObjectID, payload: protocol.Payload):
        e = self._entry(oid)
        # The event-set + callback-swap must happen under the same lock the
        # registration sites use for their check-and-append, or a registration
        # can land on the dead list after the swap (lost wakeup).
        with self._lock:
            e.payload = payload
            e.event.set()
            callbacks, e.callbacks = e.callbacks, []
        for cb in callbacks:
            cb()

    def _store_error(self, oids: List[ObjectID], err: BaseException):
        payload = protocol.serialize_value(protocol.ErrorValue(err), store=None)
        for oid in oids:
            self._store_payload(oid, payload)

    # ------------------------------------------------------------- scheduler

    def submit_task(self, fn_id: bytes, args: tuple, kwargs: dict,
                    num_returns: int = 1, options: Optional[dict] = None
                    ) -> List[ObjectRef]:
        options = options or {}
        task_id = make_task_id(self.job_id)
        args2, kwargs2, deps = self._swap_top_level_refs(args, kwargs)
        args_payload, _ = protocol.serialize_args(args2, kwargs2, store=self.store)
        return_ids = [ObjectID.from_random() for _ in range(num_returns)]
        spec = _TaskSpec(task_id, fn_id, args_payload, deps, return_ids, options)
        for rid in return_ids:
            self._entry(rid)
        self._enqueue(spec)
        return [ObjectRef(rid, core=self) for rid in return_ids]

    def _swap_top_level_refs(self, args, kwargs):
        deps: List[ObjectID] = []

        def swap(v):
            if isinstance(v, ObjectRef):
                deps.append(v.id)
                return _TopLevelDep(v.binary())
            return v

        return (tuple(swap(a) for a in args),
                {k: swap(v) for k, v in kwargs.items()}, deps)

    def _enqueue(self, spec: _TaskSpec):
        unresolved = []
        for dep in spec.deps:
            e = self._entry(dep)
            if not e.event.is_set():
                unresolved.append(e)
        spec.pending_deps = len(unresolved)
        if unresolved:
            lock = threading.Lock()

            def on_ready():
                with lock:
                    spec.pending_deps -= 1
                    ready = spec.pending_deps == 0
                if ready:
                    self._queue_ready(spec)

            for e in unresolved:
                with self._lock:
                    if e.event.is_set():
                        on_ready()
                    else:
                        e.callbacks.append(on_ready)
        else:
            self._queue_ready(spec)

    def _queue_ready(self, spec: _TaskSpec):
        if spec.actor_id is not None:
            state = self._actors[spec.actor_id]
            with self._lock:
                state.queue.append(spec)
            self._dispatch_actor(state)
        else:
            with self._lock:
                self._task_queue.append(spec)
            self._dispatch()

    def _maybe_scale_up(self):
        """Spawn an extra worker when queued tasks cannot run because every
        pool worker is blocked in a driver-side get/wait (otherwise nested
        task graphs deadlock). The reference raylet similarly releases the
        CPU of workers blocked in ray.get (worker_pool/lease semantics)."""
        with self._lock:
            if self._shutdown or not self._task_queue or self._idle:
                return
            if self._spawning > 0:
                return
            pool = [w for w in self._workers.values()
                    if w.alive and w.actor_id is None]
            if pool and all(w.blocked or not w.ready for w in pool):
                spawn = True
            else:
                spawn = False
        if spawn:
            self._spawn_worker()

    def _dispatch(self):
        while True:
            with self._lock:
                if not self._task_queue or not self._idle:
                    return
                w = self._idle.popleft()
                if not w.alive:
                    continue
                spec = self._task_queue.popleft()
                w.inflight = spec
            self._send_task(w, spec)

    def _dispatch_actor(self, state: _ActorState):
        spec = None
        failed: List[_TaskSpec] = []
        with self._lock:
            w = state.worker
            if state.dead and state.queue:
                failed = list(state.queue)
                state.queue.clear()
            elif (
                w is not None and state.ready and not state.dead
                and w.inflight is None and state.queue
            ):
                spec = state.queue.popleft()
                w.inflight = spec
        for f in failed:
            self._store_error(
                f.return_ids,
                ActorDiedError(str(state.death_cause or "actor is dead")),
            )
        if spec is not None:
            self._send_actor_call(w, spec)

    def _inline_values_for(self, deps: List[ObjectID]) -> Dict[bytes, Any]:
        out: Dict[bytes, Any] = {}
        for dep in deps:
            e = self._objects[dep]
            kind, data = e.payload
            if kind == "inline":
                out[dep.binary()] = e.payload
            else:
                out[dep.binary()] = None  # worker reads shm directly
        return out

    def _send_task(self, w: _Worker, spec: _TaskSpec):
        try:
            self._ensure_fn_on_worker(w, spec.fn_id)
            inline_values = self._inline_values_for(spec.deps)
            self._send_msg(w, (
                protocol.MSG_TASK, spec.task_id.binary(), spec.fn_id,
                spec.args_payload, inline_values,
                [r.binary() for r in spec.return_ids],
            ))
        except (OSError, EOFError, BrokenPipeError):
            self._on_worker_death(w)

    def _send_actor_call(self, w: _Worker, spec: _TaskSpec):
        try:
            inline_values = self._inline_values_for(spec.deps)
            self._send_msg(w, (
                protocol.MSG_ACTOR_CALL, spec.task_id.binary(),
                spec.actor_id.binary(), spec.method, spec.args_payload,
                inline_values, [r.binary() for r in spec.return_ids],
            ))
        except (OSError, EOFError, BrokenPipeError):
            self._on_worker_death(w)

    def _on_task_done(self, w: _Worker, task_id_b: bytes, payloads):
        with self._lock:
            spec = w.inflight
            w.inflight = None
        if spec is not None:
            for rid, payload in zip(spec.return_ids, payloads):
                self._store_payload(rid, payload)
        self._worker_now_idle(w)

    def _on_task_error(self, w: _Worker, task_id_b: bytes, err_payload):
        with self._lock:
            spec = w.inflight
            w.inflight = None
        if spec is not None:
            for rid in spec.return_ids:
                self._store_payload(rid, err_payload)
        self._worker_now_idle(w)

    def _worker_now_idle(self, w: _Worker):
        if w.actor_id is not None:
            state = self._actors.get(w.actor_id)
            if state is not None:
                self._dispatch_actor(state)
            return
        with self._lock:
            if w.alive:
                self._idle.append(w)
        self._dispatch()

    # ------------------------------------------------------------------- api

    def get_objects(self, refs: List[ObjectRef], timeout: Optional[float] = None
                    ) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            e = self._entry(ref.id)
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            if not e.event.wait(remaining):
                raise GetTimeoutError(f"get() timed out waiting for {ref}")
            out.append(protocol.raise_if_error(self._decode_entry(e)))
        return out

    def _decode_entry(self, e: _ObjectEntry):
        kind, data = e.payload
        if kind == "inline":
            return serialization.unpack(data)
        return protocol.shm_unpack(self.store, ObjectID(data))

    def put_object(self, value: Any) -> ObjectRef:
        payload = protocol.serialize_value(value, store=self.store)
        oid = ObjectID(payload[1]) if payload[0] == "shm" else ObjectID.from_random()
        self._store_payload(oid, payload)
        return ObjectRef(oid, core=self)

    def wait(self, refs: List[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = {r.id: r for r in refs}
        ready: List[ObjectRef] = []
        cond = threading.Condition()

        def notify():
            with cond:
                cond.notify_all()

        for oid in list(pending):
            e = self._entry(oid)
            with self._lock:
                if not e.event.is_set():
                    e.callbacks.append(notify)
        while True:
            ready = [r for r in refs if self._objects[r.id].event.is_set()]
            if len(ready) >= num_returns:
                break
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            with cond:
                cond.wait(remaining if remaining is None or remaining > 0 else 0)
        ready_set = {r.id for r in ready[:num_returns]}
        ready_list = [r for r in refs if r.id in ready_set]
        rest = [r for r in refs if r.id not in ready_set]
        return ready_list, rest

    def as_future(self, ref: ObjectRef):
        import asyncio

        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        e = self._entry(ref.id)

        def resolve():
            try:
                v = self._decode_entry(e)
            except BaseException as exc:  # noqa: BLE001
                loop.call_soon_threadsafe(fut.set_exception, exc)
                return
            if isinstance(v, protocol.ErrorValue):
                loop.call_soon_threadsafe(fut.set_exception, v.error)
            else:
                loop.call_soon_threadsafe(fut.set_result, v)

        with self._lock:
            if e.event.is_set():
                resolve()
            else:
                e.callbacks.append(resolve)
        return fut

    # ----------------------------------------------------------------- actors

    def create_actor(self, cls_fn_id: bytes, args: tuple, kwargs: dict,
                     opts: Optional[dict] = None) -> ActorID:
        opts = opts or {}
        actor_id = ActorID.from_random()
        args2, kwargs2, deps = self._swap_top_level_refs(args, kwargs)
        args_payload, _ = protocol.serialize_args(args2, kwargs2, store=self.store)
        state = _ActorState(actor_id, cls_fn_id, args_payload, deps, opts)
        with self._lock:
            self._actors[actor_id] = state
            name = opts.get("name")
            if name:
                if name in self._named_actors:
                    raise ValueError(f"actor name {name!r} already taken")
                self._named_actors[name] = actor_id
        self._start_actor(state)
        return actor_id

    def _start_actor(self, state: _ActorState):
        needs_tpu = state.opts.get("num_tpus", 0) > 0
        w = None
        if not needs_tpu:
            # Prefer an idle pooled worker; else spawn fresh (+ replace pool).
            with self._lock:
                w = self._idle.popleft() if self._idle else None
        if w is None:
            w = self._spawn_worker(tpu=needs_tpu)
        else:
            self._spawn_worker()  # keep task-pool capacity
        with self._lock:
            w.actor_id = state.actor_id
            state.worker = w
        self._when_worker_ready(w, lambda: self._send_create_actor(w, state))

    def _when_worker_ready(self, w: _Worker, fn):
        def poll():
            while not self._shutdown and w.alive:
                if w.ready and w.task_conn is not None:
                    fn()
                    return
                time.sleep(0.002)
        if w.ready and w.task_conn is not None:
            fn()
        else:
            threading.Thread(target=poll, daemon=True).start()

    def _send_create_actor(self, w: _Worker, state: _ActorState):
        try:
            self._ensure_fn_on_worker(w, state.cls_fn_id)
            inline_values = self._inline_values_for(state.creation_deps)
            self._send_msg(w, (
                protocol.MSG_CREATE_ACTOR, state.actor_id.binary(),
                state.cls_fn_id, state.creation_args_payload, inline_values,
                {k: v for k, v in state.opts.items() if k != "name"},
            ))
        except (OSError, EOFError, BrokenPipeError):
            self._on_worker_death(w)

    def _on_actor_ready(self, w: _Worker, actor_id: ActorID):
        state = self._actors.get(actor_id)
        if state is None:
            return
        state.ready = True
        state.creation_event.set()
        self._dispatch_actor(state)

    def _on_actor_error(self, w: _Worker, actor_id: ActorID, err_payload):
        state = self._actors.get(actor_id)
        if state is None:
            return
        try:
            v = protocol.deserialize_payload(err_payload, store=self.store)
            err = v.error if isinstance(v, protocol.ErrorValue) else v
        except Exception as e:  # noqa: BLE001
            err = ActorDiedError(f"actor constructor failed: {e}")
        self._mark_actor_dead(state, err)

    def _mark_actor_dead(self, state: _ActorState, cause: BaseException):
        with self._lock:
            if state.dead:
                return  # keep the original death cause
            state.dead = True
            state.ready = False
            state.death_cause = cause
            pending = list(state.queue)
            state.queue.clear()
        state.creation_event.set()
        err = cause if isinstance(cause, ActorDiedError) else ActorDiedError(str(cause))
        for spec in pending:
            self._store_error(spec.return_ids, err)

    def _handle_actor_worker_death(self, actor_id: ActorID):
        state = self._actors.get(actor_id)
        if state is None:
            return
        if state.restarts_left != 0 and not state.dead:
            if state.restarts_left > 0:
                state.restarts_left -= 1
            state.ready = False
            state.worker = None
            self._start_actor(state)
        else:
            self._mark_actor_dead(
                state, ActorDiedError("the actor's worker process died")
            )

    def submit_actor_task(self, actor_id: ActorID, method: str, args: tuple,
                          kwargs: dict, num_returns: int = 1) -> List[ObjectRef]:
        state = self._actors.get(actor_id)
        if state is None:
            raise ActorDiedError(f"unknown actor {actor_id}")
        task_id = make_task_id(self.job_id)
        args2, kwargs2, deps = self._swap_top_level_refs(args, kwargs)
        args_payload, _ = protocol.serialize_args(args2, kwargs2, store=self.store)
        return_ids = [ObjectID.from_random() for _ in range(num_returns)]
        for rid in return_ids:
            self._entry(rid)
        if state.dead:
            refs = [ObjectRef(rid, core=self) for rid in return_ids]
            self._store_error(
                return_ids, ActorDiedError(str(state.death_cause or "actor is dead"))
            )
            return refs
        spec = _TaskSpec(task_id, None, args_payload, deps, return_ids, {},
                         actor_id=actor_id, method=method)
        self._enqueue(spec)
        return [ObjectRef(rid, core=self) for rid in return_ids]

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        state = self._actors.get(actor_id)
        if state is None:
            return
        if no_restart:
            state.restarts_left = 0
        with self._lock:
            w = state.worker
        self._mark_actor_dead(state, ActorDiedError("actor was killed via kill()"))
        if w is not None and w.proc is not None:
            try:
                w.proc.terminate()
            except OSError:
                pass

    def get_actor_method_opts(self, actor_id: ActorID) -> dict:
        state = self._actors.get(actor_id)
        return state.opts.get("method_opts", {}) if state else {}

    def get_named_actor(self, name: str) -> ActorID:
        with self._lock:
            aid = self._named_actors.get(name)
        if aid is None:
            raise ValueError(f"no actor named {name!r}")
        return aid

    # ------------------------------------------------------------ data server

    def _data_server(self, w: _Worker):
        conn = w.data_conn
        try:
            while True:
                msg = conn.recv()
                try:
                    reply = self._handle_data_request(w, msg)
                except BaseException as e:  # noqa: BLE001
                    # Preserve the exception type (GetTimeoutError,
                    # ActorDiedError, ...) so worker-side handlers behave
                    # exactly like driver-side ones.
                    reply = ("err", protocol.serialize_value(
                        protocol.ErrorValue(e), store=None))
                conn.send(reply)
        except (EOFError, OSError):
            pass

    def _handle_data_request(self, w: _Worker, msg):
        tag = msg[0]
        if tag == protocol.REQ_GET:
            _, oid_bytes_list, timeout_ms = msg
            timeout = None if timeout_ms < 0 else timeout_ms / 1000.0
            deadline = None if timeout is None else time.monotonic() + timeout
            payloads = {}
            entries = [self._entry(ObjectID(b)) for b in oid_bytes_list]
            if not all(e.event.is_set() for e in entries):
                w.blocked = True
                self._maybe_scale_up()
            try:
                for b, e in zip(oid_bytes_list, entries):
                    remaining = None if deadline is None else max(
                        0.0, deadline - time.monotonic())
                    if not e.event.wait(remaining):
                        raise GetTimeoutError("get() timed out in worker request")
                    payloads[b] = e.payload
            finally:
                w.blocked = False
            return ("ok", payloads)
        if tag == protocol.REQ_PUT_META:
            _, oid_bytes, payload = msg
            oid = ObjectID(oid_bytes)
            self._store_payload(oid, ("shm", oid_bytes) if payload is None else payload)
            return ("ok",)
        if tag == protocol.REQ_SUBMIT:
            _, fn_id, pickled_fn, args_payload, inline_values, n_returns, options = msg
            if pickled_fn is not None:
                with self._lock:
                    self._functions.setdefault(fn_id, pickled_fn)
            deps = options.pop("__deps", [])
            task_id = make_task_id(self.job_id)
            return_ids = [ObjectID.from_random() for _ in range(n_returns)]
            for rid in return_ids:
                self._entry(rid)
            spec = _TaskSpec(task_id, fn_id, args_payload,
                             [ObjectID(d) for d in deps], return_ids, options)
            self._enqueue(spec)
            return ("ok", [r.binary() for r in return_ids])
        if tag == protocol.REQ_ACTOR_CALL:
            _, actor_id_b, method, args_payload, extra, n_returns = msg
            state = self._actors.get(ActorID(actor_id_b))
            if state is None:
                raise ActorDiedError("unknown actor")
            deps = [ObjectID(d) for d in extra.get("__deps", [])]
            task_id = make_task_id(self.job_id)
            return_ids = [ObjectID.from_random() for _ in range(n_returns)]
            for rid in return_ids:
                self._entry(rid)
            spec = _TaskSpec(task_id, None, args_payload, deps, return_ids, {},
                             actor_id=state.actor_id, method=method)
            if state.dead:
                self._store_error(
                    return_ids,
                    ActorDiedError(str(state.death_cause or "actor is dead")),
                )
            else:
                self._enqueue(spec)
            return ("ok", [r.binary() for r in return_ids])
        if tag == protocol.REQ_WAIT:
            _, oid_bytes_list, num_returns, timeout_s = msg
            refs = [ObjectRef(ObjectID(b), core=self) for b in oid_bytes_list]
            w.blocked = True
            self._maybe_scale_up()
            try:
                ready, rest = self.wait(refs, num_returns=num_returns,
                                        timeout=timeout_s)
            finally:
                w.blocked = False
            return ("ok", [x.binary() for x in ready], [x.binary() for x in rest])
        if tag == protocol.REQ_KV:
            _, op, key, value = msg
            if op == "get":
                return ("ok", self._kv.get(key))
            if op == "put":
                self._kv[key] = value
                return ("ok", None)
            if op == "del":
                self._kv.pop(key, None)
                return ("ok", None)
            raise ValueError(f"bad kv op {op}")
        if tag == protocol.REQ_GET_ACTOR:
            _, name = msg
            aid = self.get_named_actor(name)
            from ray_tpu.core.actor import ActorHandle

            handle = ActorHandle(aid, self.get_actor_method_opts(aid))
            return ("ok", protocol.serialize_value(handle, store=None))
        raise ValueError(f"unknown data request {tag!r}")

    # -------------------------------------------------------------- lifecycle

    def kv_op(self, op: str, key: str, value=None):
        if op == "get":
            return self._kv.get(key)
        if op == "put":
            self._kv[key] = value
            return None
        if op == "del":
            self._kv.pop(key, None)
            return None
        raise ValueError(op)

    def wait_for_workers(self, count: Optional[int] = None, timeout: float = 30.0):
        count = count or self.num_workers
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                n = sum(1 for w in self._workers.values() if w.ready)
            if n >= count:
                return
            time.sleep(0.005)
        raise TimeoutError(f"only some workers became ready within {timeout}s")

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            try:
                if w.task_conn is not None:
                    self._send_msg(w, (protocol.MSG_SHUTDOWN,))
            except (OSError, EOFError, BrokenPipeError):
                pass
        deadline = time.monotonic() + 2.0
        for w in workers:
            try:
                w.proc.wait(timeout=max(0.05, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                w.proc.kill()
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            os.unlink(self._sock_path)
        except OSError:
            pass
        self.store.close()
        if runtime_context.get_core_or_none() is self:
            runtime_context.set_core(None)
