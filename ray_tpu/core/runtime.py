"""Driver-side runtime: object directory, worker pool, and task scheduler.

Single-node analogue of the reference's driver CoreWorker + raylet + GCS
rolled into the driver process (the multi-node split arrives with the cluster
control plane):

- Object directory + memory store: the ownership table. The driver owns every
  object; small values live inline here, large values in the shm store
  (reference: src/ray/core_worker/store_provider/memory_store/memory_store.h,
  reference ownership model: src/ray/core_worker/reference_count.h:61).
- Worker pool: forks/pools worker processes, tracks idle/busy, restarts
  actors (reference: src/ray/raylet/worker_pool.h:153).
- Scheduler: FIFO dispatch of ready tasks (deps resolved) onto idle workers;
  per-actor ordered queues (reference: raylet local_task_manager.cc dispatch
  loop + actor_task_submitter.h ordering).
"""

from __future__ import annotations

import os
import queue
import signal
import subprocess
import sys
import threading
import time
from collections import OrderedDict, deque
from multiprocessing.connection import Listener
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.core import external_storage, fault_injection, protocol, \
    serialization
from ray_tpu.core.config import config
from ray_tpu.core.ids import (
    ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID,
    make_task_id,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core import runtime_context
from ray_tpu.core.object_store.store import ShmObjectStore, default_store_capacity
from ray_tpu.core.placement_group import (
    PlacementGroup, PlacementGroupState,
)
from ray_tpu.core.protocol import _TopLevelDep
from ray_tpu.core.resources import (
    ResourceSet, TpuSliceTopology, node_resources,
)
from ray_tpu.util.debug_lock import check_fire_outside, make_condition, \
    make_lock
from ray_tpu.exceptions import (
    ActorDiedError, ActorUnavailableError, GetTimeoutError, ObjectLostError,
    PlacementGroupError, TaskCancelledError, TaskError, WorkerCrashedError,
)


def note_freed(freed: Dict[bytes, None], ids, cap: int = 1_000_000) -> None:
    """Record eager-free tombstones (20B ids kept only so get-after-free
    errors fast instead of hanging). Past ``cap``, evict oldest-first —
    the dict is insertion-ordered — degrading a year-late get of an
    ancient freed id to a hang-with-timeout, which is acceptable. Shared
    by Runtime and ClusterCore (call under the owner's lock)."""
    for b in ids:
        freed[b] = None
    if len(freed) > cap:
        from itertools import islice

        for b in list(islice(iter(freed), len(freed) - cap // 2)):
            del freed[b]


class _ObjectEntry:
    __slots__ = ("event", "payload", "callbacks")

    def __init__(self):
        self.event = threading.Event()
        self.payload = None  # protocol.Payload once ready
        self.callbacks: List[Callable[[], None]] = []


class _Lineage:
    """Resubmittable description of a task, kept per return id so a lost
    object can be recomputed (reference: object_recovery_manager.h +
    task_manager lineage pinning). One instance is shared by all of the
    task's return ids; ``holders`` counts the table entries still
    pointing at it so the retained args container (shm payloads stay
    pinned for replay) releases exactly once."""

    __slots__ = ("task_id_hex", "fn_id", "args_payload", "deps_b",
                 "nested_b", "return_ids_b", "options", "cost", "holders",
                 "args_pinned")

    def __init__(self):
        self.args_pinned = False


class _DepsLost(Exception):
    """Raised by dependency inlining when a dep's backing value vanished
    between resolution and dispatch; carries the lost oid bytes."""

    def __init__(self, oids: List[bytes]):
        super().__init__(f"{len(oids)} task dependencies lost")
        self.oids = oids


def _task_env_key(options) -> Optional[str]:
    """Key of the isolated env a task/actor is pinned to ("<kind>:<content
    hash>"), or None. Tasks with the same key share a worker pool AND an
    env build; the kind's EnvProvider (runtime_env.register_env_provider
    — pip built-in, conda/image_uri pluggable) supplies the interpreter
    the pool's workers run."""
    renv = (options or {}).get("runtime_env") or {}
    from ray_tpu.core.runtime_env import resolve_env_provider

    res = resolve_env_provider(renv)
    if res is None:
        return None
    kind, provider, spec = res
    key = provider.env_key(spec)
    if not key:
        return None
    return f"{kind}:{key}"


class _TaskSpec:
    __slots__ = (
        "task_id", "fn_id", "args_payload", "deps", "return_ids", "options",
        "actor_id", "method", "pending_deps", "request", "pg_wire",
        "acquired_bundle", "blocked_released", "nested_deps", "cancelled",
        "retries_left", "args_pinned", "dep_pins", "submitted_ts",
        "dispatched_ts", "parent_task", "oom_kills", "env_key", "stream",
        "seq",
    )

    def __init__(self, task_id, fn_id, args_payload, deps, return_ids, options,
                 actor_id=None, method=None):
        self.task_id = task_id
        self.fn_id = fn_id
        self.args_payload = args_payload
        self.deps = deps
        self.return_ids = return_ids
        self.options = options
        self.actor_id = actor_id
        self.method = method
        self.pending_deps = 0
        # Resource accounting (filled by Runtime._prepare_request).
        self.request: Optional[ResourceSet] = None
        self.pg_wire = None          # ("pg", pg_id_bytes, bundle_index) | None
        self.acquired_bundle = None  # Bundle the request was drawn from
        self.blocked_released = False  # resources credited back while blocked
        # ObjectIDs referenced *inside* arg containers (not top-level args).
        # They are NOT scheduling dependencies (reference semantics: nested
        # refs pass through unresolved), but while unavailable the task must
        # ship alone — batched behind it, its producer could never run.
        self.nested_deps: List = []
        self.cancelled = False
        # Worker-crash retry budget (reference: max_retries,
        # src/ray/core_worker/task_manager.h:208); resolved at enqueue.
        self.retries_left: Optional[int] = None
        # memory-monitor kills survived so far (OOM retries are budgeted
        # separately from crash retries — reference: task_oom_retries)
        self.oom_kills = 0
        self.args_pinned = False
        # Real store refs taken at dispatch on shm dep containers, so spill
        # can never pull a dep out from under a worker mid-read.
        self.dep_pins: List[bytes] = []
        # timeline timestamps (recorded when task_events_enabled)
        self.submitted_ts = 0.0
        self.dispatched_ts = 0.0
        # cross-process span propagation: the submitting task's id (hex)
        # for nested submissions, None for driver-originated work
        # (reference: tracing_helper.py's trace-context injection)
        self.parent_task: Optional[str] = None
        # pip-env tasks dispatch only to workers running that env's own
        # interpreter (per-env pools — true module-version isolation)
        self.env_key: Optional[str] = _task_env_key(options)
        # num_returns="streaming": {"seed": bytes, "skip": int, "cap": int}
        # shipped to the worker so it seals yields under deterministic
        # per-index ids; None for ordinary tasks
        self.stream: Optional[dict] = None
        # Actor calls only: position in the actor's per-submission order
        # (assigned at enqueue); the actor's completion watermark keys off
        # it so a replayed already-completed call is served from the
        # store, never re-executed.
        self.seq: Optional[int] = None


class _StreamState:
    """Owner-side bookkeeping for one ``num_returns="streaming"`` task
    (reference: the per-generator ObjectRefStream in
    core_worker/task_manager.h). Index ids are deterministic
    (protocol.stream_index_id), so only counters live here:

    - ``produced``: indices sealed and reported so far (their entries are
      resolvable); the consumer may hand out refs below this watermark.
    - ``consumed``: the consumer's advance watermark — the producer's
      REQ_STREAM_CREDIT probe blocks it at ``produced - consumed >= cap``.
    - ``end_index``: total yield count once the end sentinel (or a
      mid-stream failure ref) lands; None while the stream is live.
    """

    __slots__ = ("seed", "cap", "produced", "consumed", "end_index",
                 "failed", "cond")

    def __init__(self, seed: bytes, cap: int):
        self.seed = seed
        self.cap = cap
        self.produced = 0
        self.consumed = 0
        self.end_index: Optional[int] = None
        self.failed = False
        self.cond = make_condition("_StreamState.cond")


def _fd_readable(fd, timeout) -> bool:
    """poll()-based readiness (select() raises ValueError for fds past
    FD_SETSIZE=1024 — long-lived runtimes exceed it)."""
    import select

    p = select.poll()
    p.register(fd, select.POLLIN | select.POLLERR | select.POLLHUP)
    import math

    # ceil, not truncate: selectors.py does the same so a 0.5ms wait
    # doesn't degrade to a non-blocking poll
    ms = None if timeout is None else max(0, math.ceil(timeout * 1000))
    return bool(p.poll(ms))


class _ForkedProc:
    """Popen-compatible handle for a worker forked by the zygote.

    The child is the ZYGOTE's child (kernel-reaped there via SIG_IGN),
    so Popen's wait machinery doesn't apply. Liveness and signaling go
    through a pidfd: the fd names the exact process, so a recycled pid
    can never be misread as the worker still alive, nor signaled by
    mistake (a bare signal-0 probe has both hazards). Matches the subset
    of the Popen surface the runtime uses (pid/poll/terminate/kill/
    wait)."""

    __slots__ = ("pid", "returncode", "_pidfd")

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode = None
        try:
            self._pidfd = os.pidfd_open(pid)
        except OSError:
            # already gone (or no pidfd support): treat as exited —
            # never fall back to pid probing, it can alias a recycled pid
            self._pidfd = None
            self.returncode = -1

    def poll(self):
        if self.returncode is not None:
            return self.returncode
        if _fd_readable(self._pidfd, 0):
            # pidfd becomes readable when the process exits
            self.returncode = -1
            os.close(self._pidfd)
            self._pidfd = None
        return self.returncode

    def _signal(self, sig):
        if self._pidfd is None:
            return
        try:
            signal.pidfd_send_signal(self._pidfd, sig)
        except (OSError, ProcessLookupError):
            pass

    def terminate(self):
        self._signal(signal.SIGTERM)

    def kill(self):
        self._signal(signal.SIGKILL)

    def wait(self, timeout=None):
        if self.returncode is not None:
            return self.returncode
        if not _fd_readable(self._pidfd, timeout):
            raise subprocess.TimeoutExpired("forked-worker", timeout)
        self.returncode = -1
        os.close(self._pidfd)
        self._pidfd = None
        return self.returncode


class _Worker:
    __slots__ = (
        "worker_id", "proc", "task_conn", "data_conn", "ready", "alive",
        "registered_fns", "actor_id", "inflight", "reader", "data_thread",
        "send_lock", "blocked", "oom_killed", "env_key",
    )

    def __init__(self, worker_id, proc):
        self.worker_id = worker_id
        self.proc = proc
        # pip-env workers run the env's OWN interpreter (per-env pools,
        # reference: raylet/worker_pool.h:153 env-keyed pools); None =
        # the general pool
        self.env_key: Optional[str] = None
        self.task_conn = None
        self.data_conn = None
        self.ready = False
        self.alive = True
        self.registered_fns = set()
        self.actor_id: Optional[ActorID] = None
        self.inflight: Dict[bytes, _TaskSpec] = {}
        self.reader: Optional[threading.Thread] = None
        self.data_thread: Optional[threading.Thread] = None
        # Connection.send is not thread-safe; every task_conn.send goes
        # through this lock (reader thread, dispatchers, shutdown).
        self.send_lock = make_lock("_Worker.send_lock")
        # True while the worker is blocked in a driver-side get/wait; used
        # by the scheduler to oversubscribe the pool instead of deadlocking.
        self.blocked = False
        # set by the memory monitor just before SIGKILL: death handling
        # then applies OOM retry semantics instead of crash semantics
        self.oom_killed = False


class _ActorState:
    __slots__ = (
        "actor_id", "worker", "cls_fn_id", "creation_args_payload",
        "creation_deps", "opts", "queue", "ready", "dead", "death_cause",
        "restarts_left", "name", "creation_event", "request", "pg_wire",
        "acquired_bundle", "chips", "resources_acquired", "capacity",
        "restarting", "restarting_since", "incarnation", "next_seq",
        "seq_watermark", "completed_seqs", "migrated",
    )

    def __init__(self, actor_id, cls_fn_id, args_payload, deps, opts):
        self.actor_id = actor_id
        self.worker: Optional[_Worker] = None
        self.cls_fn_id = cls_fn_id
        self.creation_args_payload = args_payload
        self.creation_deps = deps
        self.opts = opts
        # in-flight call budget the driver may keep on the worker: the
        # default pool plus every named concurrency group's threads
        # (reference: concurrency_group_manager.h:34 — per-group limits)
        self.capacity = max(1, int(opts.get("max_concurrency") or 1)) + \
            sum(int(v) for v in
                (opts.get("concurrency_groups") or {}).values())
        self.queue: deque = deque()
        self.ready = False
        self.dead = False
        self.death_cause: Optional[BaseException] = None
        self.restarts_left = opts.get("max_restarts", 0)
        self.name = opts.get("name")
        self.creation_event = threading.Event()
        self.request: Optional[ResourceSet] = None
        self.pg_wire = None
        self.acquired_bundle = None
        self.chips: List[int] = []
        self.resources_acquired = False
        # Restart FSM (reference: gcs_actor_manager.h:278 ALIVE ->
        # RESTARTING -> ALIVE|DEAD): while restarting, new calls buffer
        # (bounded by actor_restart_buffer_max / actor_restart_timeout_s)
        # and queued+in-flight calls replay to the next incarnation.
        self.restarting = False
        self.restarting_since = 0.0
        self.incarnation = 0
        # Per-actor call sequencing for exactly-once result delivery:
        # every call gets the next seq at enqueue; completion advances a
        # contiguous watermark (out-of-order completions park in
        # completed_seqs) so replays of finished calls are recognized.
        self.next_seq = 0
        self.seq_watermark = 0
        self.completed_seqs: set = set()
        # set by evict_actor (planned drain): the actor is dead HERE but
        # lives on elsewhere — reject racing calls at submit instead of
        # failing their results, so callers re-route
        self.migrated = False


def _reap_stale_shm_arenas():
    """Unlink /dev/shm arenas left by DEAD runtimes (reference: the
    raylet cleans stale plasma files on startup). A SIGKILLed node
    can't unlink its own arena; the name embeds the creator pid, so a
    dead pid means garbage. Unlinking is safe even if some zombie
    still maps the file — the mapping stays valid, only the name goes.
    """
    try:
        names = os.listdir("/dev/shm")
    except OSError:
        return
    for name in names:
        if not name.startswith("rtpu_"):
            continue
        parts = name.split("_")
        try:
            pid = int(parts[1])
        except (IndexError, ValueError):
            continue
        try:
            os.kill(pid, 0)  # alive (or EPERM: someone else's — keep)
            continue
        except ProcessLookupError:
            pass
        except OSError:
            continue
        try:
            os.unlink(os.path.join("/dev/shm", name))
        except OSError:
            pass


class Runtime:
    """The driver core client. One per driver process."""

    def __init__(self, num_workers: Optional[int] = None,
                 object_store_memory: Optional[int] = None,
                 session_name: Optional[str] = None,
                 topology: Optional[TpuSliceTopology] = None,
                 log_to_driver: Optional[bool] = None):
        self.node_id = NodeID.from_random()
        self.worker_id = WorkerID.from_random()
        self.job_id = JobID.from_random()
        self.num_workers = num_workers or max(2, (os.cpu_count() or 4))
        self._session = session_name or f"rtpu_{os.getpid()}_{self.node_id.hex()[:8]}"
        self._sock_path = os.path.join("/tmp", self._session + ".sock")
        self._authkey = os.urandom(16)

        _reap_stale_shm_arenas()
        self.store = ShmObjectStore.create(
            "/" + self._session,
            object_store_memory or default_store_capacity(),
        )
        self.store.need_space_hook = self._try_free_space
        self._spill_dir = external_storage.spill_dir_for(
            config.spill_dir, self._session)

        self._lock = make_lock("Runtime._lock")
        self._objects: Dict[ObjectID, _ObjectEntry] = {}
        # Memory management: the runtime pins every tracked shm container so
        # the LRU can never evict a live object out from under a ref; under
        # pressure, cold pinned containers are spilled to disk instead
        # (reference: local_object_manager.h spilling + pinning).
        self._spill_lock = make_lock("Runtime._spill_lock")
        self._pinned: Dict[bytes, int] = {}       # container oid -> access seq
        self._pin_seq = 0
        self._args_pins: Dict[bytes, int] = {}    # in-flight args refcounts
        self._spilled_bytes = 0
        # task lifecycle events for ray_tpu.timeline() (bounded; flag-gated)
        self._events: Optional[List[dict]] = (
            [] if config.task_events_enabled else None)
        self._functions: Dict[bytes, bytes] = {}  # fn_id -> pickled
        self._fn_cache: Dict[int, Tuple[bytes, bytes]] = {}  # id(fn) -> (fn_id, pickled)
        self._workers: Dict[WorkerID, _Worker] = {}
        self._idle: deque = deque()
        # per-pip-env worker pools (reference: worker_pool.h env-keyed
        # pools): env tasks dispatch only to these; spawned on demand
        # with the venv's own interpreter
        self._env_idle: Dict[str, deque] = {}
        self._env_queue: Dict[str, deque] = {}
        self._env_spawning: Dict[str, int] = {}
        # consecutive pre-READY deaths per env (a broken env must fail
        # its tasks after a few respawns, not crash-loop forever)
        self._env_spawn_fails: Dict[str, int] = {}
        self._task_queue: deque = deque()
        self._actors: Dict[ActorID, _ActorState] = {}
        self._named_actors: Dict[str, ActorID] = {}
        self._kv: Dict[str, Any] = {}
        # single-node mirror of the GCS pubsub plane (bounded per-channel
        # event logs with contiguous seqs; see gcs.py _op_publish/_op_poll)
        self._channels: Dict[str, list] = {}
        self._channel_seq: Dict[str, int] = {}
        self._pubsub_cond = make_condition("Runtime._pubsub_cond")
        self._packages: Dict[str, bytes] = {}  # runtime_env package store
        # eagerly-freed object ids: insertion-ordered so the tombstone cap
        # evicts oldest-first (dict preserves insertion order)
        self._freed: Dict[bytes, None] = {}
        # Lineage reconstruction (reference: object_recovery_manager.h):
        # per-return-id task descriptions, byte-bounded by
        # config.lineage_max_bytes (oldest-evicted); lost task returns
        # are recomputed by resubmitting the recorded task, up to
        # config.max_reconstructions attempts per object. ray.put and
        # freed objects are never recorded/recovered.
        self._lineage: "OrderedDict[bytes, _Lineage]" = OrderedDict()
        self._lineage_bytes = 0
        self._reconstructions: Dict[bytes, int] = {}
        self._recon_history: Dict[bytes, List[str]] = {}
        # return ids with a reconstruction resubmission in flight (their
        # entries are reset: event cleared, payload None)
        self._recovering: Dict[bytes, None] = {}
        # First-return-id -> spec, for ray.cancel lookup; entries drop when
        # the task finishes (done/error/cancel paths).
        self._cancellable: Dict[bytes, _TaskSpec] = {}
        # seed (first-return-id) -> _StreamState for every
        # num_returns="streaming" task submitted through this owner
        self._streams: Dict[bytes, _StreamState] = {}
        self._shutdown = False
        self._spawning = 0
        # Pool workers stolen by actors and not yet replaced. Replacement
        # is DEMAND-driven (reference: worker_pool.h prestart-on-backlog,
        # inverted): an actor-creation burst pays zero replacement forks;
        # the first queued task that finds the pool empty triggers one.
        self._pool_deficit = 0

        # Resource model: CPU slots == pool size; TPU chips from the slice
        # topology (detected or injected for tests).
        self.topology = topology if topology is not None else TpuSliceTopology.detect()
        self._total = ResourceSet(node_resources(
            num_cpus=self.num_workers, topology=self.topology,
        ))
        self._avail = ResourceSet(self._total.to_dict())
        self._pgs: Dict[PlacementGroupID, PlacementGroupState] = {}
        self._pending_pgs: List[PlacementGroupState] = []
        self._pending_actors: List[_ActorState] = []
        self._pg_ready_waiters: Dict[PlacementGroupID, List[ObjectID]] = {}

        # per-session worker log capture + driver streaming (reference:
        # session/logs + log_monitor.py)
        self.log_dir = os.path.join("/tmp", self._session, "logs")
        os.makedirs(self.log_dir, exist_ok=True)
        self._log_monitor = None
        if log_to_driver if log_to_driver is not None else config.log_to_driver:
            from ray_tpu.core.log_monitor import LogMonitor

            self._log_monitor = LogMonitor(
                self.log_dir,
                interval_s=config.log_monitor_interval_s).start()

        # no authkey on the listener: the HMAC handshake runs bounded in
        # a per-connection thread (a child dying mid-handshake must not
        # wedge the accept loop — see rpc._timed_handshake)
        self._listener = Listener(self._sock_path, family="AF_UNIX")
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rtpu-accept"
        )
        self._accept_thread.start()
        # zygote: pre-warmed fork template for ~10ms worker launch
        # (reference: prestarted workers, raylet/worker_pool.h:344)
        self._zygote: Optional[subprocess.Popen] = None
        self._zygote_lock = make_lock("Runtime._zygote_lock")
        if config.worker_zygote:
            try:
                with self._zygote_lock:
                    self._start_zygote_locked()
            except Exception:  # noqa: BLE001 — fall back to cold spawns
                self._zygote = None
        for _ in range(self.num_workers):
            self._spawn_worker()

        # serialized actor-start lane (see _actor_spawner_loop)
        self._actor_start_queue: "queue.Queue" = queue.Queue()
        threading.Thread(target=self._actor_spawner_loop, daemon=True,
                         name="rtpu-actor-spawner").start()

        # memory monitor + OOM kill policy (reference:
        # memory_monitor.h:52, worker_killing_policy_group_by_owner.h)
        self._oom_kill_count = 0
        if config.memory_monitor_enabled:
            threading.Thread(target=self._memory_monitor_loop,
                             daemon=True, name="rtpu-memmon").start()

    # ------------------------------------------------------------------ pool

    def _pool_env(self, tpu: bool,
                  extra_env: Optional[Dict[str, str]]) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(
            RTPU_ADDRESS=self._sock_path,
            RTPU_AUTH=self._authkey.hex(),
            RTPU_STORE="/" + self._session,
            RTPU_PKG_DIR=os.path.join("/tmp", self._session, "packages"),
            RTPU_NODE_ID=self.node_id.hex(),
        )
        if extra_env:
            env.update(extra_env)
        if not tpu:
            # Plain pool workers skip TPU/PJRT plugin registration
            # (~2s jax import per process); workers that land TPU actors
            # (num_tpus>0) are spawned with the env intact. Shared with
            # the zygote fork path — see worker_env.py.
            from ray_tpu.core.worker_env import sanitize_cpu_worker_env

            sanitize_cpu_worker_env(env)
        return env

    def _start_zygote_locked(self):
        # bufsize=0: replies are read through poll(), which must never
        # be defeated by data parked in a userspace buffer
        self._zygote = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker_main", "--zygote"],
            env=self._pool_env(tpu=False, extra_env=None),
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, bufsize=0,
            stderr=open(os.path.join(self.log_dir, "zygote.err"), "ab",
                        buffering=0),
        )
        self._zygote_ready = False

    def _fork_from_zygote(self, worker_id: WorkerID,
                          extra_env: Optional[Dict[str, str]],
                          out_path: Optional[str],
                          err_path: Optional[str]) -> Optional[int]:
        """Ask the zygote for a forked worker; returns the pid or None
        (zygote unavailable — caller cold-spawns)."""
        import json

        with self._zygote_lock:
            z = self._zygote
            if z is None or z.poll() is not None:
                if self._shutdown:
                    return None
                try:
                    self._start_zygote_locked()
                    z = self._zygote
                except Exception:  # noqa: BLE001
                    self._zygote = None
                    return None
            try:
                if not self._zygote_ready:
                    # first use: wait for the warm-import banner
                    if not _fd_readable(z.stdout, 30.0) or \
                            b"ZYGOTE_READY" not in z.stdout.readline():
                        raise RuntimeError("zygote never became ready")
                    self._zygote_ready = True
                req = {"wid": worker_id.hex(), "env": extra_env or {},
                       "out": out_path, "err": err_path}
                z.stdin.write((json.dumps(req) + "\n").encode())
                z.stdin.flush()
                if not _fd_readable(z.stdout, 30.0):
                    raise RuntimeError("zygote fork timed out")
                return int(z.stdout.readline())
            except Exception:  # noqa: BLE001 — zygote wedged: drop it
                try:
                    z.kill()
                except OSError:
                    pass
                self._zygote = None
                return None

    def _spawn_worker(self, tpu: bool = False,
                      extra_env: Optional[Dict[str, str]] = None,
                      python_exe: Optional[str] = None,
                      env_key: Optional[str] = None) -> _Worker:
        worker_id = WorkerID.from_random()
        if env_key is not None:
            # the worker knows its own env so per-task application can
            # skip re-activating it (its interpreter IS the env)
            extra_env = dict(extra_env or {})
            extra_env["RTPU_WORKER_PIP_KEY"] = env_key
        out_path = err_path = None
        if config.worker_log_redirect:
            from ray_tpu.core.log_monitor import worker_log_paths

            out_path, err_path = worker_log_paths(self.log_dir,
                                                  worker_id.hex())
        proc = None
        with self._zygote_lock:
            warm = self._zygote is not None
        if not tpu and python_exe is None and warm:
            # fast path: fork from the warm template. TPU workers need a
            # fresh interpreter (PJRT plugin registration is env-driven
            # at startup), so they always cold-spawn.
            pid = self._fork_from_zygote(worker_id, extra_env,
                                         out_path, err_path)
            if pid is not None:
                proc = _ForkedProc(pid)
        if proc is None:
            env = self._pool_env(tpu, extra_env)
            env["RTPU_WORKER_ID"] = worker_id.hex()
            out = err = None
            if out_path is not None:
                out = open(out_path, "ab", buffering=0)
                err = open(err_path, "ab", buffering=0)
            if python_exe is not None:
                # a venv interpreter must still find this framework: the
                # venv is --system-site-packages, but ray_tpu may be
                # imported from a source tree — pin it onto PYTHONPATH
                import ray_tpu as _pkg

                repo_root = os.path.dirname(
                    os.path.dirname(os.path.abspath(_pkg.__file__)))
                pp = env.get("PYTHONPATH", "")
                if repo_root not in pp.split(os.pathsep):
                    env["PYTHONPATH"] = (repo_root + os.pathsep + pp
                                         if pp else repo_root)
            try:
                proc = subprocess.Popen(
                    [python_exe or sys.executable, "-m",
                     "ray_tpu.core.worker_main"],
                    env=env, stdin=subprocess.DEVNULL, stdout=out,
                    stderr=err,
                )
            finally:
                # the child holds its own descriptors after fork/exec
                if out is not None:
                    out.close()
                if err is not None:
                    err.close()
        w = _Worker(worker_id, proc)
        w.env_key = env_key
        with self._lock:
            self._workers[worker_id] = w
            self._spawning += 1
        # a worker that dies (or wedges) BEFORE connecting has no reader
        # thread to observe its death: without this watcher it would leak
        # self._spawning forever and close the dispatch/scale-up gates
        # (env pools additionally need the death to drive their
        # crash-loop bound)
        threading.Thread(target=self._watch_until_ready, args=(w,),
                         daemon=True,
                         name=f"rtpu-spawn-{worker_id.hex()[:6]}").start()
        return w

    def _watch_until_ready(self, w: _Worker):
        deadline = time.monotonic() + config.worker_ready_timeout_s
        while (not self._shutdown and w.alive and not w.ready
               and time.monotonic() < deadline):
            if w.proc is not None and w.proc.poll() is not None:
                break
            time.sleep(0.05)
        if not self._shutdown and w.alive and not w.ready:
            if w.proc is not None:
                try:
                    w.proc.kill()
                except OSError:
                    pass
            self._on_worker_death(w)

    def _accept_loop(self):
        while not self._shutdown:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError, Exception):
                if self._shutdown:
                    return
                continue
            threading.Thread(target=self._greet_conn, args=(conn,),
                             daemon=True, name="rtpu-greet").start()

    def _greet_conn(self, conn):
        from ray_tpu.core.cluster.rpc import _timed_handshake

        try:
            _timed_handshake(conn, self._authkey, server_side=True)
            hello = conn.recv()
        except Exception:  # noqa: BLE001 — died mid-handshake
            try:
                conn.close()
            except OSError:
                pass
            return
        if hello[0] != "hello":
            conn.close()
            return
        self._register_conn(conn, hello)

    def _register_conn(self, conn, hello):
        _, kind, wid_bytes = hello
        wid = WorkerID(wid_bytes)
        with self._lock:
            w = self._workers.get(wid)
        if w is None:
            conn.close()
            return
        if kind == "task":
            w.task_conn = conn
            w.reader = threading.Thread(
                target=self._worker_reader, args=(w,), daemon=True,
                name=f"rtpu-read-{wid.hex()[:6]}",
            )
            w.reader.start()
        else:
            w.data_conn = conn
            w.data_thread = threading.Thread(
                target=self._data_server, args=(w,), daemon=True,
                name=f"rtpu-data-{wid.hex()[:6]}",
            )
            w.data_thread.start()

    # --------------------------------------------------------- reader threads

    def _worker_reader(self, w: _Worker):
        try:
            while True:
                msg = w.task_conn.recv()
                tag = msg[0]
                if tag == protocol.MSG_READY:
                    with self._lock:
                        w.ready = True
                        self._spawning -= 1
                        if w.env_key is not None:
                            # a successful startup clears the env's
                            # crash-loop strikes: only CONSECUTIVE
                            # pre-ready deaths fail the queue out
                            self._env_spawn_fails.pop(w.env_key, None)
                        # Workers pre-claimed for an actor never join the
                        # general idle pool; env workers join their env's
                        # pool.
                        if w.actor_id is None:
                            if w.env_key is not None:
                                self._env_idle.setdefault(
                                    w.env_key, deque()).append(w)
                            else:
                                self._idle.append(w)
                    if w.env_key is not None:
                        self._dispatch_env(w.env_key)
                    else:
                        self._dispatch()
                elif tag == protocol.MSG_DONE:
                    self._on_task_done(w, msg[1], msg[2])
                elif tag == protocol.MSG_STREAM_YIELD:
                    self._on_stream_yield(w, msg)
                elif tag == protocol.MSG_ERROR:
                    self._on_task_error(w, msg[1], msg[2])
                elif tag == protocol.MSG_ACTOR_READY:
                    self._on_actor_ready(w, ActorID(msg[1]))
                elif tag == protocol.MSG_ACTOR_ERROR:
                    self._on_actor_error(w, ActorID(msg[1]), msg[2])
        except (EOFError, OSError):
            pass
        finally:
            self._on_worker_death(w)

    def _on_worker_death(self, w: _Worker):
        if self._shutdown:
            return
        with self._lock:
            if not w.alive:
                return
            w.alive = False
            # cumulative unexpected-death count: the node server reports
            # it on heartbeats as the per-node task-failure signal the
            # GCS health scorer folds into quarantine decisions
            self._worker_death_count = getattr(
                self, "_worker_death_count", 0) + 1
            if not w.ready:
                # died before MSG_READY: release the spawning slot it
                # held, or scale-up/pool-repay gates stay closed forever
                self._spawning = max(0, self._spawning - 1)
            self._workers.pop(w.worker_id, None)
            try:
                self._idle.remove(w)
            except ValueError:
                pass
            if w.env_key is not None:
                try:
                    self._env_idle.get(w.env_key, deque()).remove(w)
                except ValueError:
                    pass
                if not w.ready:
                    # died before READY: likely a broken env (a pinned
                    # package shadowing a framework dep). Bound respawns
                    # or a crash-looping env would retry forever.
                    n = self._env_spawn_fails.get(w.env_key, 0) + 1
                    self._env_spawn_fails[w.env_key] = n
                else:
                    self._env_spawn_fails.pop(w.env_key, None)
            inflight = list(w.inflight.values())
            w.inflight.clear()
            actor_id = w.actor_id
            oom = w.oom_killed
            if actor_id is not None:
                # detach the dead worker NOW (not in the later restart
                # handling): a concurrent _dispatch_actor must never pop
                # queued calls into a dead worker's inflight table,
                # where they would be lost
                st = self._actors.get(actor_id)
                if st is not None and st.worker is w:
                    st.worker = None
                    st.ready = False
        if inflight:
            # Results flush per task, so inflight = not-yet-completed, in
            # dispatch order. Only the head task can have been executing
            # when the process died; the rest never started and are safe to
            # requeue on another worker. The head itself is retried while
            # its max_retries budget lasts (reference: task_manager.h
            # retries apply to system failures, not app exceptions). OOM
            # kills budget separately: the memory monitor's SIGKILL does
            # not consume max_retries (reference: task_oom_retries) —
            # only the dedicated OOM budget, after which callers see a
            # typed OutOfMemoryError.
            if actor_id is None:
                head = inflight[0]
                if oom and not head.cancelled:
                    head.oom_kills += 1
                    if (config.task_oom_retries < 0
                            or head.oom_kills <= config.task_oom_retries):
                        fail, requeue = [], inflight
                    else:
                        fail, requeue = inflight[:1], inflight[1:]
                elif head.retries_left and not head.cancelled:
                    head.retries_left -= 1
                    fail, requeue = [], inflight
                else:
                    fail, requeue = inflight[:1], inflight[1:]
            else:
                # Actor calls: at-least-once replay (reference:
                # max_task_retries, actor_task_submitter resubmission).
                # Every in-flight call whose retry budget allows it goes
                # back on the actor's queue for the restarted
                # incarnation; a call whose results the dead worker
                # already sealed is adopted straight from the store —
                # exactly-once result delivery, no re-execution.
                fail, requeue = [], []
                for spec in inflight:
                    if spec.cancelled:
                        fail.append(spec)
                    elif self._adopt_sealed_actor_result(spec):
                        pass  # served from the store
                    elif spec.retries_left != 0:
                        if spec.retries_left > 0:
                            spec.retries_left -= 1
                        requeue.append(spec)
                    else:
                        fail.append(spec)
            if oom:
                from ray_tpu.exceptions import OutOfMemoryError

                err = OutOfMemoryError(
                    f"worker {w.worker_id.hex()[:8]} was killed by the "
                    f"node memory monitor (usage above "
                    f"{config.memory_usage_threshold:.0%}) and the task "
                    f"is out of OOM retries")
            elif actor_id is not None:
                st = self._actors.get(actor_id)
                err = ActorDiedError(
                    "the actor's worker process died mid-call and the "
                    "call is out of task retries",
                    incarnation=st.incarnation if st is not None else None)
            else:
                err = WorkerCrashedError(
                    f"worker {w.worker_id.hex()[:8]} died while "
                    f"executing task")
            # Cancelled specs must not come back: report them cancelled
            # whether they were executing or merely batched behind the head.
            fail = fail + [s for s in requeue if s.cancelled]
            requeue = [s for s in requeue if not s.cancelled]
            with self._lock:
                for spec in fail + requeue:
                    # requeued specs re-acquire at dispatch; holding their
                    # old grant would double-count
                    had_request = spec.request is not None
                    self._release_spec_locked(spec)
                    if spec in requeue and had_request:
                        # release nulls the request; rebuild it so dispatch
                        # re-acquires instead of running unaccounted
                        spec.request, spec.pg_wire = self._prepare_request(
                            spec.options, is_actor=False)
            for spec in fail + requeue:
                # dispatch-time dep pins are re-taken at the next dispatch
                self._release_spec_deps(spec)
                # a worker that sealed a return container (retain=True) but
                # died before its DONE message flushed leaves a refcount-1
                # orphan; reclaim it (and clear the id for a retry's write)
                self._reap_orphan_returns(spec)
            for spec in requeue:
                if spec.stream is not None:
                    # generator replay: every index reported so far survives
                    # (shm containers are owner-pinned, inline payloads are
                    # already stored), so the retry re-runs the generator
                    # but re-seals nothing below the produced watermark
                    st = self._streams.get(spec.stream["seed"])
                    if st is not None:
                        with st.cond:
                            spec.stream = dict(spec.stream,
                                               skip=st.produced)
            for spec in fail:
                self._release_spec_args(spec)
                self._store_error(
                    spec.return_ids,
                    TaskCancelledError("task was cancelled")
                    if spec.cancelled else err)
            if requeue:
                with self._lock:
                    if actor_id is not None:
                        # replayed calls rejoin the FRONT of the actor's
                        # queue in dispatch order, ahead of calls that
                        # buffered during the restart window
                        st = self._actors.get(actor_id)
                        if st is not None:
                            st.queue.extendleft(reversed(requeue))
                    else:
                        self._task_queue.extendleft(reversed(requeue))
            self._retry_pending_pgs()
        if actor_id is not None:
            self._handle_actor_worker_death(actor_id)
        elif w.env_key is not None:
            # env pools replace on demand (in _dispatch_env — which also
            # fails the queue out once the env proves crash-looping);
            # never backfill the GENERAL pool for an env worker
            if not self._shutdown:
                self._dispatch_env(w.env_key)
        else:
            # replace pool capacity
            if not self._shutdown:
                self._spawn_worker()
        self._dispatch()

    # ------------------------------------------------------------- functions

    def register_function(self, fn) -> bytes:
        """Pickle a function once; returns its fn_id (content hash).

        The reference exports pickled functions to the GCS function table once
        per job (python/ray/_private/function_manager.py); here the registry
        lives in the driver and is lazily pushed per worker.
        """
        key = id(fn)
        cached = self._fn_cache.get(key)
        if cached is not None and cached[1] is fn:
            return cached[0]
        pickled = serialization.pack(fn)
        import hashlib

        fn_id = hashlib.blake2b(pickled, digest_size=16).digest()
        with self._lock:
            self._functions[fn_id] = pickled
        self._fn_cache[key] = (fn_id, fn)
        return fn_id

    def _send_msg(self, w: _Worker, msg) -> None:
        with w.send_lock:
            # rtpu-lint: disable=L2 — send_lock exists precisely to
            # serialize frames on this worker's task_conn; nothing else
            # is ever taken under it, so it cannot participate in a cycle
            w.task_conn.send(msg)

    def _ensure_fn_on_worker(self, w: _Worker, fn_id: bytes):
        if fn_id not in w.registered_fns:
            with self._lock:
                pickled = self._functions[fn_id]
            self._send_msg(w, (protocol.MSG_REGISTER_FN, fn_id, pickled))
            w.registered_fns.add(fn_id)

    # ------------------------------------------------------------ object dir

    def _entry(self, oid: ObjectID) -> _ObjectEntry:
        with self._lock:
            e = self._objects.get(oid)
            if e is None:
                e = _ObjectEntry()
                if oid.binary() in self._freed:
                    # freed ids keep only a 20-byte tombstone; a get
                    # resurrects this transient error entry instead of
                    # hanging on a value that will never arrive
                    from ray_tpu.exceptions import ObjectLostError

                    e.payload = protocol.serialize_value(
                        protocol.ErrorValue(ObjectLostError(
                            f"object {oid} was freed")), store=None)
                    e.event.set()
                self._objects[oid] = e
            return e

    def _store_payload(self, oid: ObjectID, payload: protocol.Payload):
        e = self._entry(oid)
        # The event-set + callback-swap must happen under the same lock the
        # registration sites use for their check-and-append, or a registration
        # can land on the dead list after the swap (lost wakeup).
        with self._lock:
            e.payload = payload
            e.event.set()
            self._recovering.pop(oid.binary(), None)
            callbacks, e.callbacks = e.callbacks, []
        # Pin tracked shm containers against LRU eviction (spill handles
        # pressure). Only self-named containers (container id == entry id)
        # are spill candidates; that is every put/task-return container.
        if payload[0] == "shm" and payload[1] == oid.binary():
            self._pin_container(payload[1])
        # Foreign callables (dep-ready continuations, as_future
        # resolvers): must dispatch with no runtime lock held — a
        # callback that re-enters the runtime deadlocks the holder
        # (the PR 5 _enqueue bug). Sanitizer-enforced when armed.
        check_fire_outside("Runtime._store_payload")
        for cb in callbacks:
            cb()

    # ------------------------------------------------------ pinning + spill

    def _pin_container(self, oid_b: bytes):
        """Adopt the retained creator reference of a container as this
        owner's tracking pin (the handoff protocol: every task-return/put
        container is sealed with retain=True, so it arrives refcount>=1 and
        there is never an evictable window)."""
        with self._spill_lock:
            self._pin_seq += 1
            self._pinned[oid_b] = self._pin_seq  # insert or LRU-touch

    def _pin_args(self, oid_b: bytes):
        """Adopt the retained ref of an args container for a task's flight
        time (refcounted: actor restarts re-pin the same container)."""
        with self._spill_lock:
            n = self._args_pins.get(oid_b, 0)
            self._args_pins[oid_b] = n + 1
        if n:
            # extra pins beyond the adopted creator ref take a real one
            try:
                self.store.get(ObjectID(oid_b), timeout_ms=0)
            # rtpu-lint: disable=L4 — best-effort extra pin: if the
            # container already left the store (evicted/spilled), the
            # task's dependency resolution recovers it anyway
            except Exception:  # noqa: BLE001
                pass

    def _unpin_args(self, oid_b: bytes, delete: bool = True):
        # Symmetric with _pin_args: every pin holds one ref (the first
        # adopts the retained creator ref, later ones took real refs), so
        # every unpin releases one; the last also deletes.
        with self._spill_lock:
            n = self._args_pins.get(oid_b, 0) - 1
            if n > 0:
                self._args_pins[oid_b] = n
            else:
                self._args_pins.pop(oid_b, None)
        oid = ObjectID(oid_b)
        try:
            self.store.release(oid)
            if n <= 0 and delete:
                self.store.delete(oid)
        # rtpu-lint: disable=L4 — the container may have been spilled,
        # freed, or the store closed mid-shutdown; all mean the pin is
        # already moot
        except Exception:  # noqa: BLE001
            pass

    def _pin_spec_args(self, spec: _TaskSpec):
        p = spec.args_payload
        if p is not None and p[0] == "shm" and not spec.args_pinned:
            spec.args_pinned = True
            self._pin_args(p[1])

    def _release_spec_args(self, spec: _TaskSpec):
        # Only task/actor-CALL specs pass through here; actor CREATION
        # payloads live in _ActorState (kept pinned for restarts).
        p = spec.args_payload
        if spec.args_pinned and p is not None and p[0] == "shm":
            spec.args_pinned = False
            self._unpin_args(p[1])

    def free_objects(self, oid_bytes_list: List[bytes],
                     return_ids: bool = False):
        """Eagerly delete objects (reference: internal_api.free) —
        complements the pin+spill lifetime model for workloads that know
        an object is dead. Unresolved ids are skipped; subsequent gets of
        a freed id surface ObjectLostError, and the id's lineage entry is
        invalidated so reconstruction is never attempted (free means
        dead). Returns the count actually freed."""
        from ray_tpu.exceptions import ObjectLostError

        freed_ids: List[bytes] = []
        for oid_b in oid_bytes_list:
            oid = ObjectID(oid_b)
            with self._lock:
                e = self._objects.get(oid)
                if (e is None or not e.event.is_set()
                        or oid_b in self._freed):
                    continue
                note_freed(self._freed, (oid_b,))
                payload = e.payload
            kind, data = payload
            if kind == "shm":
                with self._spill_lock:
                    pinned = self._pinned.pop(oid_b, None) is not None
                if pinned:
                    try:
                        self.store.release(oid)
                        self.store.delete(oid)
                    # rtpu-lint: disable=L4 — already evicted or store
                    # closed: either way the object is gone, which is
                    # what free() wants
                    except Exception:  # noqa: BLE001
                        pass
                else:
                    # the pressure-spill thread won the pin: the payload
                    # may have flipped shm->spilled after our read —
                    # re-read so the spill file is reclaimed, not leaked
                    with self._lock:
                        e2 = self._objects.get(oid)
                        payload = e2.payload if e2 is not None else payload
                    kind, data = payload
            if kind == "spilled":
                path = data[0] if isinstance(data, tuple) else data
                external_storage.delete(path)
                if isinstance(data, tuple):
                    with self._spill_lock:
                        self._spilled_bytes -= data[1]
            # drop the table entry entirely: periodic fire-and-forget
            # callers (e.g. load reports) can then free their refs
            # without the object table growing; the _freed tombstone
            # keeps later gets erroring instead of hanging
            with self._lock:
                e = self._objects.pop(oid, None)
                unresolved = e is not None and not e.event.is_set()
                if unresolved:
                    # concurrent waiters on a just-freed id: re-insert so
                    # _store_error below resolves them with the error
                    self._objects[oid] = e
            if unresolved:
                self._store_error(
                    [oid], ObjectLostError(f"object {oid} was freed"))
            self._cancellable.pop(oid_b, None)
            self._drop_lineage(oid_b)
            freed_ids.append(oid_b)
        return freed_ids if return_ids else len(freed_ids)

    def _try_free_space(self, nbytes: int) -> bool:
        """Spill cold tracked containers to disk until ``nbytes`` are freed.
        Called by the store's pressure hook (driver-side) and by workers via
        REQ_NEED_SPACE. Returns True when anything was spilled."""
        with self._spill_lock:
            candidates = sorted(self._pinned.items(), key=lambda kv: kv[1])
        freed = 0
        for oid_b, _ in candidates:
            if freed >= nbytes:
                break
            freed += self._spill_one(oid_b)
        return freed > 0

    def _spill_one(self, oid_b: bytes) -> int:
        oid = ObjectID(oid_b)
        # Safe to spill only when our tracking pin is the sole reference —
        # a reader's zero-copy view must never lose its backing pages.
        if self.store.refcount(oid) != 1:
            return 0
        try:
            view = self.store.get(oid, timeout_ms=0)
        except Exception:  # noqa: BLE001
            return 0
        try:
            try:
                path, size = external_storage.write(self._spill_dir,
                                                    oid.hex(), view)
            except Exception:  # noqa: BLE001 — transient backend error
                # (s3 hiccup etc.): skip this candidate; the caller's
                # put must see store pressure, never a raw fsspec error
                return 0
        finally:
            del view
            try:
                self.store.release(oid)  # the read pin just taken
            # rtpu-lint: disable=L4 — pin release on a store that may be
            # closing; failing to release cannot be worse than raising
            # out of the spill path
            except Exception:  # noqa: BLE001
                pass
        with self._lock:
            e = self._objects.get(oid)
            swapped = (e is not None and e.payload == ("shm", oid_b)
                       and oid_b not in self._freed)
            if swapped:
                e.payload = ("spilled", (path, size))
        if not swapped:
            # a concurrent free() won (payload is now a freed-error marker
            # or gone): discard the file we just wrote — accounting it
            # would leak disk and inflate _spilled_bytes forever
            external_storage.delete(path)
            return 0
        with self._spill_lock:
            self._pinned.pop(oid_b, None)
            self._spilled_bytes += size
        try:
            self.store.release(oid)  # the tracking pin
            self.store.delete(oid)
        # rtpu-lint: disable=L4 — the shm copy just became redundant
        # (payload points at the spill file); if reclaim races a close
        # or eviction the copy is gone anyway
        except Exception:  # noqa: BLE001
            pass
        if fault_injection.enabled():
            # 'spill' fault site: lose the file the moment the payload
            # moved to disk (torn write / reclaimed scratch volume)
            action = fault_injection.fire("spill", oid.hex())
            if action == "delete":
                external_storage.delete(path)
            elif action == "corrupt":
                external_storage.corrupt(path)
        return size

    def _store_error(self, oids: List[ObjectID], err: BaseException):
        payload = protocol.serialize_value(protocol.ErrorValue(err), store=None)
        for oid in oids:
            self._cancellable.pop(oid.binary(), None)
            st = self._streams.get(oid.binary())
            if st is not None:
                # A streaming task's seed id is never resolved directly;
                # surface the failure as the stream's final ref instead
                # (the consumer's next() hands it out, its get() raises,
                # then the iterator ends).
                self._fail_stream(st, payload)
            else:
                self._store_payload(oid, payload)

    # ------------------------------------------------------ streaming returns

    def _register_stream(self, seed: bytes) -> "_StreamState":
        st = _StreamState(seed, int(config.streaming_generator_backpressure))
        with self._lock:
            self._streams[seed] = st
        return st

    def _stream_opts(self, seed: bytes) -> dict:
        """Wire dict shipped to the worker alongside the task."""
        return {"seed": seed, "skip": 0,
                "cap": int(config.streaming_generator_backpressure)}

    def _on_stream_yield(self, w: "_Worker", msg):
        """MSG_STREAM_YIELD: one streamed return sealed by the worker.
        Adopt the payload under its deterministic index id and advance the
        produced watermark so blocked ``next()`` calls wake."""
        _, task_id_b, seed, index, rid_b, payload, is_end = msg
        st = self._streams.get(seed)
        self._store_payload(ObjectID(rid_b), payload)
        if st is None:
            return  # stream unknown (late report after shutdown/reap)
        with st.cond:
            if is_end:
                if st.end_index is None:
                    st.end_index = index
            elif index >= st.produced:
                st.produced = index + 1
            st.cond.notify_all()

    def _fail_stream(self, st: "_StreamState", err_payload):
        """Terminate a stream with an error: seal the payload at the next
        unproduced index (consumers blocked there wake and get a ref whose
        get() raises) and end the stream right after it. A stream that
        already ended normally is left untouched."""
        with st.cond:
            if st.end_index is not None:
                return
            idx = st.produced
            st.produced = idx + 1
            st.end_index = idx + 1
            st.failed = True
            st.cond.notify_all()
        self._store_payload(
            ObjectID(protocol.stream_index_id(st.seed, idx)), err_payload)

    def stream_next(self, seed: bytes, index: int,
                    timeout: Optional[float] = None, owner=None):
        """Blocking driver-side next for ObjectRefGenerator: returns
        ("ref", rid_bytes) once index is produced or ("end", count) once
        the stream ended before it. ``owner`` is a cluster-path routing
        hint; a single-node runtime owns every stream it knows."""
        from ray_tpu.exceptions import ObjectTimeoutError

        st = self._streams.get(seed)
        if st is None:
            raise ValueError(f"unknown stream {seed.hex()}")
        deadline = None if timeout is None else time.monotonic() + timeout
        with st.cond:
            while True:
                kind = self._stream_poll_locked(st, index)
                if kind is not None:
                    return kind
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise ObjectTimeoutError(
                        f"stream {seed.hex()} index {index} not produced "
                        f"within {timeout}s")
                st.cond.wait(remaining)

    def _stream_poll_locked(self, st: "_StreamState", index: int):
        """One non-blocking poll; holds st.cond."""
        if st.end_index is not None and index >= st.end_index:
            return ("end", st.end_index)
        if index < st.produced:
            return ("ref", protocol.stream_index_id(st.seed, index))
        return None

    def stream_consumed(self, seed: bytes, index: int, owner=None):
        """The consumer advanced past ``index``: raise the consumed
        watermark so the producer's backpressure credit frees up."""
        st = self._streams.get(seed)
        if st is None:
            return
        with st.cond:
            if index + 1 > st.consumed:
                st.consumed = index + 1
            st.cond.notify_all()

    # ---------------------------------------------------------------- lineage

    def _record_lineage(self, spec: _TaskSpec):
        """Keep enough of a plain task's description to resubmit it if a
        return is lost. Shm args containers are retained (one _pin_args
        ref) for the lineage entry's lifetime and charged at their full
        size, so the lineage_max_bytes budget — and store pressure via
        _try_free_space — bounds what replayability costs."""
        p = spec.args_payload
        lin = _Lineage()
        lin.task_id_hex = spec.task_id.hex()
        lin.fn_id = spec.fn_id
        lin.args_payload = p
        lin.deps_b = [d.binary() for d in spec.deps]
        lin.nested_b = [d.binary() for d in spec.nested_deps]
        lin.return_ids_b = [r.binary() for r in spec.return_ids]
        lin.options = dict(spec.options)
        cost = 64
        if p is not None and p[0] == "inline":
            cost += len(p[1])
        elif p is not None and p[0] == "shm":
            self._pin_args(p[1])
            lin.args_pinned = True
            try:
                mv = self.store.get(ObjectID(p[1]), timeout_ms=0)
                cost += mv.nbytes
                del mv
                self.store.release(ObjectID(p[1]))
            except Exception:  # noqa: BLE001
                cost += 64
        lin.cost = cost
        lin.holders = len(lin.return_ids_b)
        to_unpin: List[bytes] = []
        with self._lock:
            for rid_b in lin.return_ids_b:
                old = self._lineage.pop(rid_b, None)
                if old is not None:
                    self._lineage_bytes -= old.cost
                    if self._drop_lineage_holder_locked(old):
                        to_unpin.append(old.args_payload[1])
                self._lineage[rid_b] = lin
                self._lineage_bytes += lin.cost
            to_unpin.extend(self._evict_lineage_locked())
        for oid_b in to_unpin:
            self._unpin_args(oid_b)

    def _drop_lineage_holder_locked(self, lin: _Lineage) -> bool:
        """Returns True when the caller must release the entry's retained
        args container (last holder gone)."""
        lin.holders -= 1
        return lin.holders == 0 and lin.args_pinned

    def _evict_lineage_locked(self) -> List[bytes]:
        """Enforce the byte budget; returns args containers to unpin."""
        to_unpin: List[bytes] = []
        while self._lineage_bytes > config.lineage_max_bytes and self._lineage:
            rid_b, old = self._lineage.popitem(last=False)
            self._lineage_bytes -= old.cost
            if self._drop_lineage_holder_locked(old):
                to_unpin.append(old.args_payload[1])
        return to_unpin

    def _drop_lineage(self, oid_b: bytes):
        """Invalidate one return id's lineage (free means dead)."""
        with self._lock:
            lin = self._lineage.pop(oid_b, None)
            unpin = False
            if lin is not None:
                self._lineage_bytes -= lin.cost
                unpin = self._drop_lineage_holder_locked(lin)
            self._reconstructions.pop(oid_b, None)
            self._recon_history.pop(oid_b, None)
        if unpin:
            self._unpin_args(lin.args_payload[1])

    def _payload_lost(self, payload) -> bool:
        """True when a resolved payload's backing value is gone (shm
        container evicted / spill file deleted). Inline payloads and
        None (entry reset for an in-flight reconstruction) are not
        lost."""
        if payload is None:
            return False
        kind, data = payload
        if kind == "shm":
            return not self.store.contains(ObjectID(data))
        if kind == "spilled":
            path = data[0] if isinstance(data, tuple) else data
            return external_storage.size(path) is None
        return False

    def _object_available(self, oid_b: bytes) -> bool:
        with self._lock:
            e = self._objects.get(ObjectID(oid_b))
            if e is None:
                return False
            if not e.event.is_set():
                return True  # pending: a producer/reconstruction resolves it
            payload = e.payload
        return not self._payload_lost(payload)

    def _lost_error(self, oid_b: bytes, cause=None) -> ObjectLostError:
        """The enriched terminal error for an unrecoverable object:
        names the producing task (when lineage knows it) and the
        reconstruction attempt history."""
        oid = ObjectID(oid_b)
        with self._lock:
            freed = oid_b in self._freed
            lin = self._lineage.get(oid_b)
            history = list(self._recon_history.get(oid_b, ()))
            n = self._reconstructions.get(oid_b, 0)
        if freed:
            why = "it was freed (free means dead)"
        elif lin is None:
            why = ("no lineage is recorded (ray_tpu.put values and "
                   "lineage-evicted task returns are not reconstructable)")
        elif n >= max(0, config.max_reconstructions):
            why = (f"the reconstruction budget is exhausted "
                   f"(max_reconstructions={config.max_reconstructions})")
        else:
            why = "reconstruction failed"
        msg = f"object {oid} is lost and cannot be reconstructed: {why}"
        if cause is not None:
            msg += f" [loss: {str(cause)[:200]}]"
        return ObjectLostError(msg, task_id=lin.task_id_hex if lin else "",
                               attempts=history)

    def _recover_object(self, oid_b: bytes, cause=None, depth: int = 0
                        ) -> bool:
        """Attempt lineage reconstruction of a lost object by
        resubmitting its producing task (recursively recovering lost
        upstream deps). Returns True when the object's entry WILL
        resolve again — a resubmission is in flight, possibly started by
        another thread, possibly resolving to an error — so the caller
        should re-wait on the entry. Returns False when the object is
        unrecoverable and the entry is untouched (caller raises
        _lost_error)."""
        if depth > 10:
            return False
        reset_ids: List[bytes] = []
        with self._lock:
            if oid_b in self._freed:
                return False
            e = self._objects.get(ObjectID(oid_b))
            if e is not None and not e.event.is_set():
                return True  # already being reproduced
            lin = self._lineage.get(oid_b)
            if lin is None:
                return False
            # find which of the task's returns are actually lost; a
            # concurrent recovery may already have replaced the value
            lost = [rid_b for rid_b in lin.return_ids_b
                    if (re := self._objects.get(ObjectID(rid_b))) is not None
                    and re.event.is_set() and self._payload_lost(re.payload)]
            if oid_b not in lost:
                if cause is None:
                    return True  # probe says alive: concurrent recovery won
                # the caller OBSERVED a failed decode — trust it over the
                # existence probe (a corrupt spill file still stats fine)
                lost.append(oid_b)
            n = self._reconstructions.get(oid_b, 0)
            if n >= config.max_reconstructions:
                return False
            self._reconstructions[oid_b] = n + 1
            self._recon_history.setdefault(oid_b, []).append(
                f"attempt {n + 1}: resubmitted task {lin.task_id_hex[:16]} "
                f"({type(cause).__name__ if cause is not None else 'loss'})")
            spilled_cleanup = []
            for rid_b in lost:
                re_ = self._objects[ObjectID(rid_b)]
                if re_.payload is not None and re_.payload[0] == "spilled":
                    spilled_cleanup.append(re_.payload[1])
                re_.payload = None
                re_.event.clear()
                self._recovering[rid_b] = None
                reset_ids.append(rid_b)
        with self._spill_lock:
            for rid_b in reset_ids:
                self._pinned.pop(rid_b, None)
        for data in spilled_cleanup:
            path = data[0] if isinstance(data, tuple) else data
            external_storage.delete(path)
            if isinstance(data, tuple):
                with self._spill_lock:
                    self._spilled_bytes -= data[1]
        # upstream deps must be readable before the task re-runs
        for dep_b in list(lin.deps_b) + list(lin.nested_b):
            if not self._object_available(dep_b):
                if not self._recover_object(dep_b, cause, depth + 1):
                    self._finish_failed_recovery(
                        reset_ids, self._lost_error(
                            oid_b, cause=ObjectLostError(
                                f"upstream dependency "
                                f"{ObjectID(dep_b)} is unrecoverable")))
                    return True
        try:
            task_id = make_task_id(self.job_id)
            spec = _TaskSpec(task_id, lin.fn_id, lin.args_payload,
                             [ObjectID(b) for b in lin.deps_b],
                             [ObjectID(b) for b in lin.return_ids_b],
                             dict(lin.options))
            spec.nested_deps = [ObjectID(b) for b in lin.nested_b]
            spec.request, spec.pg_wire = self._prepare_request(
                spec.options, is_actor=False)
            self._cancellable[lin.return_ids_b[0]] = spec
            self._enqueue(spec)
        except BaseException as err:  # noqa: BLE001 — e.g. PG removed
            self._finish_failed_recovery(
                reset_ids, self._lost_error(oid_b, cause=err))
        return True

    def _finish_failed_recovery(self, reset_ids: List[bytes],
                                err: ObjectLostError):
        """Resolve reset entries to the terminal error so waiters wake
        instead of hanging on a reconstruction that cannot happen."""
        self._store_error([ObjectID(b) for b in reset_ids], err)

    def _apply_get_fault(self, oid: ObjectID):
        """'get' fault site: lose the object deterministically just
        before a driver-side read decodes it."""
        action = fault_injection.fire("get", oid.hex())
        if action == "evict":
            fault_injection.evict_object(self, oid)
        elif action == "delete_spill":
            fault_injection.delete_spill_file(self, oid)
        elif action == "corrupt_spill":
            fault_injection.corrupt_spill_file(self, oid)

    # ------------------------------------------------------------- scheduler

    def submit_task(self, fn_id: bytes, args: tuple, kwargs: dict,
                    num_returns=1, options: Optional[dict] = None
                    ) -> List[ObjectRef]:
        options = options or {}
        streaming = num_returns == "streaming"
        if streaming:
            # one pre-generated return id doubles as the stream seed; the
            # yields live under deterministic per-index ids derived from it
            num_returns = 1
        task_id = make_task_id(self.job_id)
        args2, kwargs2, deps = self._swap_top_level_refs(args, kwargs)
        args_payload, nested = protocol.serialize_args(
            args2, kwargs2, store=self.store)
        return_ids = [ObjectID.from_random() for _ in range(num_returns)]
        spec = _TaskSpec(task_id, fn_id, args_payload, deps, return_ids, options)
        spec.nested_deps = [r.id for r in nested]
        spec.request, spec.pg_wire = self._prepare_request(options, is_actor=False)
        for rid in return_ids:
            self._entry(rid)
        self._cancellable[return_ids[0].binary()] = spec
        if streaming:
            seed = return_ids[0].binary()
            spec.stream = self._stream_opts(seed)
            self._register_stream(seed)
        else:
            # streaming tasks replay via the worker-death requeue path
            # (skip=produced); lost index objects surface the enriched
            # ObjectLostError instead of lineage resubmission
            self._record_lineage(spec)
        self._enqueue(spec)
        return [ObjectRef(rid, core=self) for rid in return_ids]

    def _swap_top_level_refs(self, args, kwargs):
        deps: List[ObjectID] = []

        def swap(v):
            if isinstance(v, ObjectRef):
                deps.append(v.id)
                return _TopLevelDep(v.binary())
            return v

        return (tuple(swap(a) for a in args),
                {k: swap(v) for k, v in kwargs.items()}, deps)

    def _enqueue(self, spec: _TaskSpec):
        if self._spec_pg_removed(spec):
            self._store_error(spec.return_ids, PlacementGroupError(
                "placement group was removed"))
            return
        if spec.retries_left is None:
            if spec.actor_id is not None:
                # per-call option > per-method/class default > 0 (actor
                # calls are not retried unless asked — reference:
                # max_task_retries defaults to 0, python/ray/actor.py)
                state = self._actors.get(spec.actor_id)
                default = (int(state.opts.get("max_task_retries", 0))
                           if state is not None else 0)
                spec.retries_left = int(
                    (spec.options or {}).get("max_task_retries", default))
            else:
                spec.retries_left = int(spec.options.get(
                    "max_retries", config.task_max_retries))
        if spec.actor_id is not None and spec.seq is None:
            state = self._actors.get(spec.actor_id)
            if state is not None:
                with self._lock:
                    spec.seq = state.next_seq
                    state.next_seq += 1
        if self._events is not None and not spec.submitted_ts:
            spec.submitted_ts = time.time()
        self._pin_spec_args(spec)
        unresolved = []
        for dep in spec.deps:
            e = self._entry(dep)
            if not e.event.is_set():
                unresolved.append(e)
        spec.pending_deps = len(unresolved)
        if unresolved:
            lock = make_lock("Runtime._enqueue.<deps>")

            def on_ready():
                with lock:
                    spec.pending_deps -= 1
                    ready = spec.pending_deps == 0
                if ready:
                    self._queue_ready(spec)

            for e in unresolved:
                # check-and-append stays under the lock (lost-wakeup
                # guard), but the callback must fire OUTSIDE it: on_ready
                # of the last pending dep runs _queue_ready, which
                # re-acquires the (non-reentrant) lock — invoking it here
                # would deadlock the submitting thread against itself
                fire = False
                with self._lock:
                    if e.event.is_set():
                        fire = True
                    else:
                        e.callbacks.append(on_ready)
                if fire:
                    check_fire_outside("Runtime._enqueue.on_ready")
                    on_ready()
        else:
            self._queue_ready(spec)

    def _spec_pg_removed(self, spec) -> bool:
        if spec.pg_wire is None:
            return False
        with self._lock:
            pg = self._pgs.get(PlacementGroupID(spec.pg_wire[1]))
        return pg is None or pg.removed

    def _queue_ready(self, spec: _TaskSpec):
        if spec.cancelled:
            # Never dispatched -> no resources were acquired; nothing to
            # release. (cancel_task already failed the return ids.)
            self._store_error(spec.return_ids,
                              TaskCancelledError("task was cancelled"))
            return
        # Deps may resolve long after submission; re-check the PG here so a
        # task whose group vanished while it waited fails instead of hanging.
        if spec.actor_id is None and self._spec_pg_removed(spec):
            self._store_error(spec.return_ids, PlacementGroupError(
                "placement group was removed"))
            return
        if spec.actor_id is not None:
            state = self._actors[spec.actor_id]
            with self._lock:
                # the submit-path migrated check and the evict mark are
                # not atomic; re-check under the lock the eviction marks
                # under, so a call racing the mark gets a RETRYABLE
                # error instead of joining a queue nothing will drain
                if state.dead and state.migrated:
                    evicted = True
                else:
                    evicted = False
                    state.queue.append(spec)
            if evicted:
                self._store_error(spec.return_ids, ActorUnavailableError(
                    "actor migrated off this node mid-submit; the new "
                    "incarnation is registering — retry"))
                return
            self._dispatch_actor(state)
        else:
            with self._lock:
                self._task_queue.append(spec)
            self._dispatch()

    def _mark_worker_blocked(self, w: _Worker, task_id_b: Optional[bytes]):
        """Worker enters a blocking get/wait: release the *blocking task's*
        resources so dependents can run (reference: raylet releases CPU of
        workers blocked in ray.get), and scale the pool if everyone is
        blocked."""
        released = False
        with self._lock:
            if not w.blocked:
                w.blocked = True
                spec = w.inflight.get(task_id_b) if task_id_b else None
                if spec is not None and spec.request is not None \
                        and spec.acquired_bundle is None \
                        and not spec.blocked_released:
                    self._avail = self._avail + spec.request
                    spec.blocked_released = True
                    released = True
        if released:
            self._retry_pending_pgs()
            self._dispatch()
        self._maybe_scale_up()

    def _unmark_worker_blocked(self, w: _Worker, task_id_b: Optional[bytes]):
        with self._lock:
            if w.blocked:
                w.blocked = False
                spec = w.inflight.get(task_id_b) if task_id_b else None
                if spec is not None and spec.blocked_released:
                    # Oversubscription debt is allowed; it drains as other
                    # tasks finish.
                    self._avail = self._avail.subtract_unchecked(spec.request)
                    spec.blocked_released = False

    def _maybe_scale_up(self):
        """Spawn an extra worker when queued tasks cannot run because every
        pool worker is blocked in a driver-side get/wait (otherwise nested
        task graphs deadlock). The reference raylet similarly releases the
        CPU of workers blocked in ray.get (worker_pool/lease semantics)."""
        with self._lock:
            if self._shutdown or not self._task_queue or self._idle:
                return
            if self._spawning > 0:
                return
            pool = [w for w in self._workers.values()
                    if w.alive and w.actor_id is None]
            # an EMPTY pool (every worker stolen by actors under lazy
            # replacement) must also scale, or queued tasks starve
            spawn = not pool or all(w.blocked or not w.ready
                                    for w in pool)
        if spawn:
            self._spawn_worker()

    @property
    def MAX_DISPATCH_BATCH(self):
        from ray_tpu.core.config import config

        return config.max_dispatch_batch

    def _route_env_specs(self):
        """Move pip-env tasks from the general queue into their env's
        queue (dispatched by _dispatch_env to env-keyed workers only —
        they never touch the general pool)."""
        routed: List[_TaskSpec] = []
        with self._lock:
            if not any(s.env_key for s in self._task_queue):
                return
            keep: deque = deque()
            for s in self._task_queue:
                (routed if s.env_key else keep).append(s)
            self._task_queue = keep
            keys = set()
            for s in routed:
                self._env_queue.setdefault(s.env_key, deque()).append(s)
                keys.add(s.env_key)
        for key in keys:
            self._dispatch_env(key)

    def _dispatch_env(self, key: str):
        """Dispatch queued env tasks onto idle env workers, spawning the
        env's worker (venv build + cold start with the venv interpreter)
        when none exists."""
        while True:
            renv = None
            send = None
            failed = None
            with self._lock:
                q = self._env_queue.get(key)
                idle = self._env_idle.get(key)
                while idle and not idle[0].alive:
                    idle.popleft()
                if not q:
                    return
                if idle:
                    spec = q[0]
                    if not self._try_acquire_spec_locked(spec):
                        return
                    q.popleft()
                    w = idle.popleft()
                    w.inflight[spec.task_id.binary()] = spec
                    send = (w, spec)
                else:
                    failed = None
                    alive_env = sum(1 for x in self._workers.values()
                                    if x.alive and x.env_key == key
                                    and x.actor_id is None)
                    # grow the env pool with demand (bounded by the
                    # general pool size) — one worker per env would
                    # serialize a deep env queue while the node idles
                    cap = max(1, self.num_workers)
                    want = min(len(q), cap)
                    if (not self._env_spawning.get(key)
                            and alive_env < want):
                        if self._env_spawn_fails.get(key, 0) >= 3:
                            # crash-looping env: fail its queue out
                            failed = list(q)
                            q.clear()
                        else:
                            self._env_spawning[key] = 1
                            renv = q[0].options.get("runtime_env")
            if send is not None:
                self._send_task_batch(send[0], [send[1]])
                continue
            if failed:
                err = RuntimeError(
                    f"pip env {key} workers crashed repeatedly before "
                    "becoming ready — the env is likely broken (a "
                    "pinned package shadowing a framework dependency?)")
                for spec in failed:
                    self._store_error(spec.return_ids, err)
                return
            if renv is not None:
                threading.Thread(target=self._spawn_env_worker,
                                 args=(key, renv), daemon=True).start()
            return

    def _spawn_env_worker(self, key: str, runtime_env: dict):
        """Background: build (or reuse) the venv, then cold-spawn a
        worker running ITS interpreter. Build failures fail every task
        queued for the env — there is no worker that could ever run
        them."""
        from ray_tpu.core import runtime_env as _re

        try:
            kind, provider, spec = _re.resolve_env_provider(runtime_env)
            prep = provider.prepare(spec)
            self._spawn_worker(python_exe=prep.python_exe, env_key=key,
                               extra_env=prep.env_vars or None)
        except Exception as e:  # noqa: BLE001 — fail the env's tasks
            with self._lock:
                q = self._env_queue.pop(key, deque())
            # queued env specs were never resource-acquired (acquisition
            # happens at dispatch), so there is NOTHING to release here —
            # releasing would credit the pool for grants never taken
            for spec in q:
                self._store_error(spec.return_ids, RuntimeError(
                    f"runtime_env setup failed: {e!r}"))
        finally:
            with self._lock:
                self._env_spawning[key] = 0
        # pre-ready death (broken env, bogus provider exe) is observed by
        # the shared _watch_until_ready watcher every spawn starts — it
        # feeds _on_worker_death, which drives this env's crash-loop
        # bound / respawn via _dispatch_env

    def _dispatch(self):
        self._route_env_specs()
        # env queues also drain on GENERAL events (resource release,
        # completions): an env task that failed resource acquisition
        # with an idle env worker would otherwise never be retried
        with self._lock:
            env_keys = [k for k, q in self._env_queue.items() if q]
        for k in env_keys:
            self._dispatch_env(k)
        while True:
            batch = []
            with self._lock:
                while self._idle and not self._idle[0].alive:
                    self._idle.popleft()
                if not self._task_queue or not self._idle:
                    # queued work + drained pool: repay ONE stolen
                    # worker (actor creations defer replacement forks
                    # to exactly this moment — see _pool_deficit)
                    if (self._task_queue and not self._idle
                            and not self._shutdown
                            and self._spawning == 0
                            and self._pool_deficit > 0):
                        self._pool_deficit -= 1
                        threading.Thread(
                            target=self._repay_pool_deficit,
                            daemon=True).start()
                    return
                # Fair division: divide the queue across the whole pool
                # (busy workers rejoin soon), so one early-finishing worker
                # cannot swallow work the others would run in parallel.
                pool = sum(1 for x in self._workers.values()
                           if x.alive and x.actor_id is None
                           and x.env_key is None) or 1
                cap = max(1, min(
                    self.MAX_DISPATCH_BATCH,
                    -(-len(self._task_queue) // pool),
                ))
                i = 0
                while i < len(self._task_queue) and len(batch) < cap:
                    spec = self._task_queue[i]
                    if spec.request is not None or spec.pg_wire is not None:
                        # Resource-bearing specs ship alone so their
                        # resources release at *their* completion, not at
                        # the end of an unrelated batch.
                        if batch:
                            break
                        if self._try_acquire_spec_locked(spec):
                            batch.append(spec)
                            del self._task_queue[i]
                        else:
                            i += 1
                        if batch:
                            break
                        continue
                    if spec.nested_deps and self._nested_unready_locked(spec):
                        # May block in get() on a not-yet-produced object:
                        # ship alone, so its producer is never ordered
                        # behind it in the same worker's batch (blocked-
                        # worker scale-up then guarantees progress).
                        if batch:
                            break
                        batch.append(spec)
                        del self._task_queue[i]
                        break
                    batch.append(spec)
                    del self._task_queue[i]
                if not batch:
                    return
                w = self._idle.popleft()
                for spec in batch:
                    w.inflight[spec.task_id.binary()] = spec
            self._send_task_batch(w, batch)

    # ----------------------------------------------------------- resources

    def _prepare_request(self, options: dict, is_actor: bool):
        """Normalize task/actor options into (ResourceSet, pg_wire)."""
        req = {}
        num_cpus = options.get("num_cpus")
        if num_cpus is None:
            num_cpus = 0.0 if is_actor else 1.0
        if num_cpus:
            req["CPU"] = float(num_cpus)
        num_tpus = options.get("num_tpus", 0)
        if num_tpus:
            if not is_actor:
                raise ValueError(
                    "num_tpus is actor-scoped in this release: TPU chips are "
                    "bound to dedicated worker processes at spawn time (PJRT "
                    "plugin registration happens at interpreter startup). "
                    "Wrap TPU work in an actor with num_tpus=N."
                )
            req["TPU"] = float(num_tpus)
        for k, v in (options.get("resources") or {}).items():
            req[k] = req.get(k, 0) + float(v)
        strategy = options.get("scheduling_strategy")
        pg_wire = None
        if strategy is not None and hasattr(strategy, "_to_wire"):
            wire = strategy._to_wire()
            if wire[0] == "pg":
                pg_wire = wire
        elif isinstance(strategy, tuple) and strategy and strategy[0] == "pg":
            pg_wire = strategy
        if not is_actor and pg_wire is None and req == {"CPU": 1.0}:
            # The worker slot IS the CPU for a default task (pool size ==
            # CPU count): gate on worker availability only, which lets the
            # dispatcher pipeline batches onto workers. Non-default
            # requests (custom resources, fractional CPU, PG bundles) go
            # through explicit accounting.
            return None, None
        return ResourceSet(req), pg_wire

    def _nested_unready_locked(self, spec) -> bool:
        """True if any ObjectID nested inside the task's args is not yet
        produced (missing entry counts as unready). Caller holds _lock."""
        for oid in spec.nested_deps:
            e = self._objects.get(oid)
            if e is None or not e.event.is_set():
                return True
        return False

    def _try_acquire_spec_locked(self, spec) -> bool:
        """Try to acquire spec.request from its pool. Caller holds _lock."""
        if spec.request is None:
            return True
        if spec.pg_wire is not None:
            state = self._pgs.get(PlacementGroupID(spec.pg_wire[1]))
            if state is None or state.removed or not state.ready_event.is_set():
                return False
            bundle = state.find_bundle(spec.request, spec.pg_wire[2])
            if bundle is None:
                return False
            bundle.acquire(spec.request)
            spec.acquired_bundle = bundle
            return True
        if spec.request.is_subset_of(self._avail):
            self._avail = self._avail - spec.request
            return True
        return False

    def _release_spec_locked(self, spec):
        if spec.request is None:
            return
        if spec.acquired_bundle is not None:
            spec.acquired_bundle.release(spec.request)
            # Resources of a *removed* PG's bundle must flow back to the
            # node pool, not die inside the dead bundle.
            if spec.pg_wire is not None:
                pg = self._pgs.get(PlacementGroupID(spec.pg_wire[1]))
                if pg is None or pg.removed:
                    self._avail = self._avail + spec.request
            spec.acquired_bundle = None
        elif spec.blocked_released:
            spec.blocked_released = False  # already credited at block time
        else:
            self._avail = self._avail + spec.request
        spec.request = None

    def _dispatch_actor(self, state: _ActorState):
        specs: List[_TaskSpec] = []
        failed: List[_TaskSpec] = []
        served: List[_TaskSpec] = []
        with self._lock:
            w = state.worker
            if state.dead and state.queue:
                failed = list(state.queue)
                state.queue.clear()
            elif w is not None and state.ready and not state.dead:
                # keep up to `capacity` calls in flight: with
                # max_concurrency / concurrency groups the worker-side
                # pools overlap them (default actors stay FIFO, cap 1)
                while (state.queue
                       and len(w.inflight) < state.capacity):
                    spec = state.queue.popleft()
                    if (spec.seq is not None
                            and (spec.seq < state.seq_watermark
                                 or spec.seq in state.completed_seqs)):
                        # replay of a call that already completed (its
                        # result is sealed in the store): deliver from
                        # the store, never re-execute the side effect
                        served.append(spec)
                        continue
                    w.inflight[spec.task_id.binary()] = spec
                    specs.append(spec)
        for spec in served:
            self._release_spec_args(spec)
            self._release_spec_deps(spec)
            self._cancellable.pop(spec.return_ids[0].binary(), None)
        for f in failed:
            self._store_error(f.return_ids, self._actor_dead_error(state))
        for spec in specs:
            self._send_actor_call(w, spec)

    def _inline_values_for(self, deps: List[ObjectID],
                           spec: Optional[_TaskSpec] = None
                           ) -> Dict[bytes, Any]:
        """Raises _DepsLost (when dispatching a spec) if a dep's backing
        value vanished between resolution and dispatch — the dispatcher
        then reconstructs the deps and requeues the spec instead of
        shipping a read that is known to fail worker-side."""
        out: Dict[bytes, Any] = {}
        lost: List[bytes] = []
        with self._lock:
            entries = {dep: self._objects[dep] for dep in deps}
        for dep in deps:
            e = entries[dep]
            payload = e.payload
            if payload is None:
                # entry reset: its reconstruction is already in flight
                lost.append(dep.binary())
                continue
            kind, data = payload
            if kind == "shm":
                # Pin the container for the task's flight time: with only
                # the tracking pin, spill could delete it between dispatch
                # and the worker's shm read.
                pinned = False
                if spec is not None:
                    try:
                        self.store.get(ObjectID(data), timeout_ms=0)
                        spec.dep_pins.append(data)
                        pinned = True
                    # rtpu-lint: disable=L4 — pin miss (raced a spill or
                    # eviction) is an expected outcome: the not-pinned
                    # branch below re-reads the entry and recovers
                    except Exception:  # noqa: BLE001
                        pass
                if spec is not None and not pinned:
                    # raced a spill: the entry's payload has moved to disk —
                    # re-read and ship the current descriptor in-message
                    with self._lock:
                        refreshed = self._objects[dep].payload
                    if refreshed is None or refreshed[0] == "shm":
                        # not a spill race: the container is truly gone
                        lost.append(dep.binary())
                    else:
                        out[dep.binary()] = refreshed
                else:
                    out[dep.binary()] = None  # worker reads shm directly
            elif (kind == "spilled" and spec is not None
                  and self._payload_lost(payload)):
                lost.append(dep.binary())
            else:
                # inline and spilled payload descriptors travel in-message
                # (the worker opens spill files itself — same host)
                out[dep.binary()] = payload
        if lost and spec is not None:
            self._release_spec_deps(spec)  # pins taken before the loss hit
            raise _DepsLost(lost)
        return out

    def _release_spec_deps(self, spec: _TaskSpec):
        pins, spec.dep_pins = spec.dep_pins, []
        for oid_b in pins:
            try:
                self.store.release(ObjectID(oid_b))
            # rtpu-lint: disable=L4 — flight-pin release races frees and
            # store shutdown; a stale pin on a gone object is a no-op
            except Exception:  # noqa: BLE001
                pass

    def _reap_orphan_returns(self, spec: _TaskSpec):
        """Reclaim sealed-but-unreported return containers of a crashed
        worker (refcount 1 from seal-retain, never adopted). A container
        the worker only CREATED (died mid-write) still leaks its creator
        ref — reclaiming that needs dead-process ref accounting in the C
        store, a narrower window left for a future round."""
        rids = list(spec.return_ids)
        if spec.stream is not None:
            # a streaming worker may have sealed index `produced` without
            # its MSG_STREAM_YIELD flushing; that container is the same
            # kind of orphan
            st = self._streams.get(spec.stream["seed"])
            if st is not None:
                with st.cond:
                    nxt = st.produced
                rids.append(ObjectID(
                    protocol.stream_index_id(spec.stream["seed"], nxt)))
        for rid in rids:
            rid_b = rid.binary()
            with self._spill_lock:
                if rid_b in self._pinned:
                    continue  # adopted: the result actually arrived
            with self._lock:
                e = self._objects.get(rid)
                if e is not None and e.event.is_set():
                    continue
            try:
                if self.store.contains(rid):
                    self.store.release(rid)
                    self.store.delete(rid)
            # rtpu-lint: disable=L4 — reaping after a worker crash is
            # best-effort: a container that cannot be reclaimed now is
            # only a leak, and raising would abort the death handling
            except Exception:  # noqa: BLE001
                pass

    def _requeue_lost_dep_spec(self, w: _Worker, spec: _TaskSpec,
                               lost_oids: List[bytes]):
        """A dep's value vanished between resolution and dispatch: pull
        the spec back off the worker, kick off reconstruction of the
        lost deps, and requeue it (it re-waits on the reset entries).
        Unrecoverable deps fail the task with the enriched error."""
        with self._lock:
            w.inflight.pop(spec.task_id.binary(), None)
            self._release_spec_locked(spec)
        self._release_spec_deps(spec)
        for oid_b in lost_oids:
            if not self._recover_object(oid_b):
                self._release_spec_args(spec)
                self._store_error(spec.return_ids, self._lost_error(oid_b))
                return
        if spec.actor_id is None:
            # re-derive the resource request released above; actor-call
            # specs carry none (the actor's worker holds its resources)
            spec.request, spec.pg_wire = self._prepare_request(
                spec.options, is_actor=False)
        self._enqueue(spec)

    def _send_task_batch(self, w: _Worker, batch: List[_TaskSpec]):
        try:
            entries = []
            sent = []
            for spec in batch:
                # unconditional: the OOM kill policy sorts on this
                spec.dispatched_ts = time.time()
                self._ensure_fn_on_worker(w, spec.fn_id)
                try:
                    inline_values = self._inline_values_for(spec.deps, spec)
                except _DepsLost as lost:
                    self._requeue_lost_dep_spec(w, spec, lost.oids)
                    continue
                entries.append((
                    spec.task_id.binary(), spec.fn_id, spec.args_payload,
                    inline_values, [r.binary() for r in spec.return_ids],
                    spec.options.get("runtime_env"), spec.stream,
                ))
                sent.append(spec)
            if entries:
                self._send_msg(w, (protocol.MSG_TASK_BATCH, entries))
            if fault_injection.enabled() and w.proc is not None:
                # 'dispatch' fault site: the worker dies right after
                # receiving the batch (keyed by function id)
                for spec in sent:
                    key = spec.fn_id.hex() if spec.fn_id else ""
                    if fault_injection.fire("dispatch", key) == "kill_worker":
                        try:
                            os.kill(w.proc.pid, signal.SIGKILL)
                        except ProcessLookupError:
                            pass
                        break
        except (OSError, EOFError, BrokenPipeError):
            self._on_worker_death(w)

    def _send_actor_call(self, w: _Worker, spec: _TaskSpec):
        try:
            # unconditional: the OOM kill policy sorts on this
            spec.dispatched_ts = time.time()
            fault = None
            if fault_injection.enabled():
                # 'actor_call' fault site, keyed "<actor hex>:<method>":
                # 'drop' loses the dispatch (the call stays in flight but
                # the worker never sees it), 'kill_worker' SIGKILLs the
                # actor's worker right after the send
                fault = fault_injection.fire(
                    "actor_call",
                    f"{spec.actor_id.hex()}:{spec.method}")
                if fault == "drop":
                    return
            try:
                inline_values = self._inline_values_for(spec.deps, spec)
            except _DepsLost as lost:
                self._requeue_lost_dep_spec(w, spec, lost.oids)
                return
            self._send_msg(w, (
                protocol.MSG_ACTOR_CALL, spec.task_id.binary(),
                spec.actor_id.binary(), spec.method, spec.args_payload,
                inline_values, [r.binary() for r in spec.return_ids],
                spec.stream,
            ))
            if fault == "kill_worker" and w.proc is not None:
                try:
                    os.kill(w.proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
        except (OSError, EOFError, BrokenPipeError):
            self._on_worker_death(w)

    def _on_task_done(self, w: _Worker, task_id_b: bytes, payloads):
        with self._lock:
            spec = w.inflight.pop(task_id_b, None)
            if spec is not None:
                self._release_spec_locked(spec)
        if spec is not None:
            if self._events is not None and len(self._events) < 200_000:
                now = time.time()
                self._events.append({
                    "task_id": spec.task_id.hex(),
                    "parent_task_id": spec.parent_task,
                    "fn": (spec.method if spec.method
                           else (spec.fn_id.hex()[:8] if spec.fn_id
                                 else "task")),
                    "actor": spec.actor_id.hex() if spec.actor_id else None,
                    "worker": w.worker_id.hex()[:8],
                    "pid": w.proc.pid if w.proc else 0,
                    "submitted": spec.submitted_ts or now,
                    "dispatched": spec.dispatched_ts or now,
                    "done": now,
                })
            self._release_spec_args(spec)
            self._release_spec_deps(spec)
            if spec.cancelled:
                # cancel() was promised while the task sat batched behind
                # the worker's head task; honor it even though the task ran.
                self._store_error(spec.return_ids,
                                  TaskCancelledError("task was cancelled"))
            else:
                self._cancellable.pop(spec.return_ids[0].binary(), None)
                for rid, payload in zip(spec.return_ids, payloads):
                    self._store_payload(rid, payload)
            self._actor_call_completed(spec)
        self._retry_pending_pgs()
        self._worker_now_idle(w)

    def _on_task_error(self, w: _Worker, task_id_b: bytes, err_payload):
        with self._lock:
            spec = w.inflight.pop(task_id_b, None)
            if spec is not None:
                self._release_spec_locked(spec)
        if spec is not None:
            self._release_spec_deps(spec)
            if (not spec.cancelled
                    and self._maybe_retry_actor_error(spec, err_payload)):
                # retry_exceptions replay: the args stay pinned for the
                # re-execution, the error is never delivered
                self._retry_pending_pgs()
                self._worker_now_idle(w)
                return
            self._release_spec_args(spec)
            if spec.cancelled:
                # SIGINT-interrupted execution surfaces as a cancellation,
                # not as the raw KeyboardInterrupt TaskError.
                self._store_error(spec.return_ids,
                                  TaskCancelledError("task was cancelled"))
            else:
                self._cancellable.pop(spec.return_ids[0].binary(), None)
                st = (self._streams.get(spec.stream["seed"])
                      if spec.stream is not None else None)
                if st is not None:
                    # mid-stream app error: becomes the stream's final
                    # (raising) ref instead of resolving the seed id
                    self._fail_stream(st, err_payload)
                else:
                    for rid in spec.return_ids:
                        self._store_payload(rid, err_payload)
            self._actor_call_completed(spec)
        self._retry_pending_pgs()
        self._worker_now_idle(w)

    def _worker_now_idle(self, w: _Worker):
        if w.actor_id is not None:
            state = self._actors.get(w.actor_id)
            if state is not None:
                self._dispatch_actor(state)
            return
        if w.env_key is not None:
            retire_env = False
            with self._lock:
                q = self._env_queue.get(w.env_key)
                idle = self._env_idle.setdefault(w.env_key, deque())
                if (not q) and idle and not w.inflight:
                    # keep ONE warm worker per env; retire the surplus
                    retire_env = True
                    self._workers.pop(w.worker_id, None)
                    w.alive = False
                elif w.alive and not w.inflight and w not in idle:
                    idle.append(w)
            if retire_env:
                try:
                    self._send_msg(w, (protocol.MSG_SHUTDOWN,))
                except (OSError, EOFError, BrokenPipeError):
                    pass  # already exiting on its own
            else:
                self._dispatch_env(w.env_key)
            return
        retire = False
        with self._lock:
            pool = sum(1 for x in self._workers.values()
                       if x.alive and x.actor_id is None
                       and x.env_key is None)
            if (not self._task_queue and pool > self.num_workers
                    and not w.inflight):
                # Surplus worker from blocked-get scale-up: retire it so the
                # pool (and the implicit CPU cap on default tasks) returns
                # to its configured size.
                self._workers.pop(w.worker_id, None)
                w.alive = False
                retire = True
            elif w.alive and not w.inflight and w not in self._idle:
                self._idle.append(w)
        if retire:
            try:
                self._send_msg(w, (protocol.MSG_SHUTDOWN,))
            except (OSError, EOFError, BrokenPipeError):
                pass
            return
        self._dispatch()

    # ------------------------------------------------------------------- api

    def get_objects(self, refs: List[ObjectRef], timeout: Optional[float] = None
                    ) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        return [protocol.raise_if_error(self._get_one(ref, deadline))
                for ref in refs]

    def _get_one(self, ref: ObjectRef, deadline: Optional[float]):
        """Resolve + decode one object, transparently reconstructing a
        lost value from lineage: on ObjectLostError the producing task is
        resubmitted (recursively recovering lost upstream deps) and the
        wait restarts, up to config.max_reconstructions attempts."""
        e = self._entry(ref.id)
        oid_b = ref.id.binary()
        while True:
            remaining = None if deadline is None else max(
                0.0, deadline - time.monotonic())
            if not e.event.wait(remaining):
                raise GetTimeoutError(f"get() timed out waiting for {ref}")
            if fault_injection.enabled():
                self._apply_get_fault(ref.id)
            try:
                return self._decode_entry(e)
            except ObjectLostError as err:
                if not self._recover_object(oid_b, err):
                    raise self._lost_error(oid_b, err) from None

    def _decode_entry(self, e: _ObjectEntry):
        payload = e.payload
        if payload is None:
            # entry reset by a concurrent reconstruction between our
            # event.wait and this read; callers re-wait
            raise ObjectLostError("object is being reconstructed")
        kind, data = payload
        if kind == "inline":
            return serialization.unpack(data)
        if kind == "spilled":
            return protocol.spilled_unpack(data)
        try:
            return protocol.shm_unpack(self.store, ObjectID(data))
        except ObjectLostError:
            # raced a concurrent spill: the payload may have moved to disk
            kind2, data2 = e.payload if e.payload is not None else (None, None)
            if kind2 == "spilled":
                return protocol.spilled_unpack(data2)
            raise

    def put_object(self, value: Any) -> ObjectRef:
        payload = protocol.serialize_value(value, store=self.store)
        oid = ObjectID(payload[1]) if payload[0] == "shm" else ObjectID.from_random()
        self._store_payload(oid, payload)
        return ObjectRef(oid, core=self)

    def wait(self, refs: List[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = {r.id: r for r in refs}
        ready: List[ObjectRef] = []
        cond = make_condition("Runtime.wait.<cond>")

        def notify():
            with cond:
                cond.notify_all()

        for oid in list(pending):
            e = self._entry(oid)
            with self._lock:
                if not e.event.is_set():
                    e.callbacks.append(notify)
        while True:
            with self._lock:
                ready = [r for r in refs
                         if self._objects[r.id].event.is_set()]
            if len(ready) >= num_returns:
                break
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            with cond:
                cond.wait(remaining if remaining is None or remaining > 0 else 0)
        ready_set = {r.id for r in ready[:num_returns]}
        ready_list = [r for r in refs if r.id in ready_set]
        rest = [r for r in refs if r.id not in ready_set]
        return ready_list, rest

    def as_future(self, ref: ObjectRef):
        import asyncio

        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        e = self._entry(ref.id)

        def resolve():
            try:
                v = self._decode_entry(e)
            except ObjectLostError as exc:
                oid_b = ref.id.binary()
                if self._recover_object(oid_b, exc):
                    # re-arm for the reconstructed value
                    with self._lock:
                        if not e.event.is_set():
                            e.callbacks.append(resolve)
                            return
                    resolve()
                else:
                    loop.call_soon_threadsafe(
                        fut.set_exception, self._lost_error(oid_b, exc))
                return
            except BaseException as exc:  # noqa: BLE001
                loop.call_soon_threadsafe(fut.set_exception, exc)
                return
            if isinstance(v, protocol.ErrorValue):
                loop.call_soon_threadsafe(fut.set_exception, v.error)
            else:
                loop.call_soon_threadsafe(fut.set_result, v)

        # same discipline as _enqueue's dep registration: check-and-append
        # under the lock, but run the callback outside it — resolve() can
        # enter reconstruction, which re-acquires the non-reentrant lock
        fire = False
        with self._lock:
            if e.event.is_set():
                fire = True
            else:
                e.callbacks.append(resolve)
        if fire:
            resolve()
        return fut

    # ----------------------------------------------------------------- actors

    def create_actor(self, cls_fn_id: bytes, args: tuple, kwargs: dict,
                     opts: Optional[dict] = None) -> ActorID:
        opts = opts or {}
        args2, kwargs2, deps = self._swap_top_level_refs(args, kwargs)
        args_payload, _ = protocol.serialize_args(args2, kwargs2, store=self.store)
        return self._create_actor_from_payload(cls_fn_id, args_payload, deps, opts)

    def _create_actor_from_payload(self, cls_fn_id: bytes, args_payload,
                                   deps: List[ObjectID], opts: dict,
                                   actor_id: Optional[ActorID] = None
                                   ) -> ActorID:
        # A caller-specified id lets the cluster layer recreate a restarted
        # actor under its original identity on a different node.
        actor_id = actor_id or ActorID.from_random()
        if args_payload is not None and args_payload[0] == "shm":
            # adopt the retained creation-args ref for the actor's lifetime
            # (restarts re-read the payload); released at terminal death
            self._pin_args(args_payload[1])
        state = _ActorState(actor_id, cls_fn_id, args_payload, deps, opts)
        state.request, state.pg_wire = self._prepare_request(opts, is_actor=True)
        if self._spec_pg_removed(state):
            with self._lock:
                self._actors[actor_id] = state
            self._mark_actor_dead(state, ActorDiedError(
                "placement group was removed before the actor was placed"))
            return actor_id
        with self._lock:
            self._actors[actor_id] = state
            name = opts.get("name")
            if name:
                if name in self._named_actors:
                    raise ValueError(f"actor name {name!r} already taken")
                self._named_actors[name] = actor_id
            placed = self._try_acquire_actor_locked(state)
            if not placed:
                self._pending_actors.append(state)
        if placed:
            # Start (fork + handshake) OFF the caller's thread: the
            # creator only needs the id it already chose, and method
            # calls queue on the actor state until MSG_ACTOR_READY —
            # so a creation burst pipelines instead of paying a
            # serialized fork per reply (reference: actor creation is
            # async task submission, core_worker.cc SubmitActorCreationTask).
            # One spawner thread per runtime: concurrent forks on few
            # cores thrash (page-table churn + context switches).
            self._actor_start_queue.put(state)
        return actor_id

    def _actor_spawner_loop(self):
        while not self._shutdown:
            try:
                state = self._actor_start_queue.get(timeout=0.5)
            except queue.Empty:
                continue
            if state.dead:
                continue  # killed while queued: never fork for it
            try:
                self._start_actor(state)
            except Exception as e:  # noqa: BLE001
                # transient start failure (fork EAGAIN, zygote respawn):
                # spend the restart budget like a worker death would,
                # only then declare the actor dead
                if state.restarts_left != 0 and not state.dead:
                    if state.restarts_left > 0:
                        state.restarts_left -= 1
                    time.sleep(0.05)
                    self._actor_start_queue.put(state)
                    continue
                try:
                    self._mark_actor_dead(state, ActorDiedError(
                        f"actor failed to start: {e!r}"))
                # rtpu-lint: disable=L4 — crash-proof daemon loop: the
                # spawner thread serves every actor; failing to mark one
                # dead must not stop it from starting the rest
                except Exception:  # noqa: BLE001
                    pass

    def _start_actor(self, state: _ActorState):
        needs_tpu = bool(state.chips) or state.opts.get("num_tpus", 0) > 0
        env_key = _task_env_key(state.opts)
        if env_key is not None and not needs_tpu:
            # pip-env actor: a DEDICATED worker running the venv's own
            # interpreter (never a pooled one — its module versions
            # must come from the env). Venv build is cached; the actor
            # start queue thread absorbs the one-time cost.
            from ray_tpu.core import runtime_env as _re

            renv = state.opts.get("runtime_env") or {}
            kind, provider, spec = _re.resolve_env_provider(renv)
            prep = provider.prepare(spec)
            w = self._spawn_worker(python_exe=prep.python_exe,
                                   env_key=env_key,
                                   extra_env=prep.env_vars or None)
            with self._lock:
                w.actor_id = state.actor_id
                state.worker = w
                died = state.dead
            if died:
                if w.proc is not None:
                    try:
                        w.proc.terminate()
                    except OSError:
                        pass
                return
            self._when_worker_ready(
                w, lambda: self._send_create_actor(w, state))
            return
        w = None
        if not needs_tpu:
            # Prefer an idle pooled worker; else spawn fresh (+ replace pool).
            with self._lock:
                w = self._idle.popleft() if self._idle else None
        if w is None:
            extra_env = {}
            if state.chips:
                chips_str = ",".join(str(c) for c in state.chips)
                # Same env contract the reference sets for TPU workers
                # (accelerators/tpu.py:158 set_current_process_visible_accelerator_ids)
                extra_env["TPU_VISIBLE_CHIPS"] = chips_str
                extra_env["RTPU_TPU_CHIPS"] = chips_str
            w = self._spawn_worker(tpu=needs_tpu, extra_env=extra_env)
        else:
            # replace task-pool capacity lazily (see _pool_deficit): the
            # fork (~10-25ms even from the zygote) must not serialize
            # into every create_actor RPC reply, and a burst of actor
            # creations should not pay a fork per actor at all
            with self._lock:
                self._pool_deficit += 1
        with self._lock:
            w.actor_id = state.actor_id
            state.worker = w
            died = state.dead
        if died:
            # killed between the queue pop and here: reclaim the worker
            # instead of pinning it to a dead actor
            if w.proc is not None:
                try:
                    w.proc.terminate()
                except OSError:
                    pass
            return
        self._when_worker_ready(w, lambda: self._send_create_actor(w, state))

    def _repay_pool_deficit(self):
        """Spawn ONE replacement for a stolen pool worker (called when
        queued work finds the pool empty). On failure the debt stays."""
        try:
            self._spawn_worker()
            return
        # rtpu-lint: disable=L4 — spawn can fail many ways (fork EAGAIN,
        # racing shutdown); the deficit below records the debt so a later
        # caller retries, which beats failing THIS task submission
        except Exception:  # noqa: BLE001 — racing shutdown
            pass
        with self._lock:
            self._pool_deficit += 1

    def _when_worker_ready(self, w: _Worker, fn):
        def poll():
            while not self._shutdown and w.alive:
                if w.ready and w.task_conn is not None:
                    fn()
                    return
                time.sleep(0.002)
        if w.ready and w.task_conn is not None:
            fn()
        else:
            threading.Thread(target=poll, daemon=True).start()

    def _send_create_actor(self, w: _Worker, state: _ActorState):
        try:
            self._ensure_fn_on_worker(w, state.cls_fn_id)
            inline_values = self._inline_values_for(state.creation_deps)
            self._send_msg(w, (
                protocol.MSG_CREATE_ACTOR, state.actor_id.binary(),
                state.cls_fn_id, state.creation_args_payload, inline_values,
                {k: v for k, v in state.opts.items() if k != "name"},
            ))
        except (OSError, EOFError, BrokenPipeError):
            self._on_worker_death(w)

    def _on_actor_ready(self, w: _Worker, actor_id: ActorID):
        state = self._actors.get(actor_id)
        if state is None:
            return
        with self._lock:
            restarted = state.restarting
            state.restarting = False
            state.ready = True
        state.creation_event.set()
        if restarted:
            # RESTARTING -> ALIVE: buffered + replayed calls drain to the
            # new incarnation in _dispatch_actor below
            self._publish_actor_state(state, "ALIVE")
        self._dispatch_actor(state)

    def _publish_actor_state(self, state: _ActorState, st: str):
        """Broadcast an actor FSM transition (ALIVE/RESTARTING/DEAD) on
        the ``actor_state`` pubsub channel. Single-node this lands in the
        Runtime's local mirror; in cluster mode the overriding core
        routes it to the GCS so every node and driver observes the same
        buffer/raise/replay semantics."""
        try:
            self.pubsub_op("publish", "actor_state", {
                "actor_id": state.actor_id.binary(),
                "state": st,
                "incarnation": state.incarnation,
                "restarts_left": state.restarts_left,
                "name": state.name,
            })
        # rtpu-lint: disable=L4 — the publication is advisory (a
        # subscriber that misses a transition re-reads the actor table);
        # losing it must never break the death/restart handling itself
        except Exception:  # noqa: BLE001
            pass

    def _actor_dead_error(self, state: _ActorState) -> ActorDiedError:
        """Terminal-death error enriched with the cause, the restart
        budget spent, and the incarnation that failed."""
        opts_max = int(state.opts.get("max_restarts", 0) or 0)
        consumed = (state.incarnation if opts_max < 0
                    else opts_max - max(0, state.restarts_left))
        return ActorDiedError(
            "actor is dead",
            cause=str(state.death_cause or "unknown"),
            restarts_consumed=consumed,
            incarnation=state.incarnation)

    def _check_actor_admission(self, state: _ActorState):
        """While an actor is RESTARTING new calls buffer on its queue —
        but only actor_restart_buffer_max of them, and only until the
        restart has run for actor_restart_timeout_s. Past either bound
        the caller gets ActorUnavailableError: unlike ActorDiedError the
        actor may come back, so callers can retry later."""
        if state.dead or not state.restarting:
            return
        if (time.monotonic() - state.restarting_since
                > config.actor_restart_timeout_s):
            raise ActorUnavailableError(
                f"actor {state.actor_id.hex()[:12]} has been RESTARTING "
                f"for more than actor_restart_timeout_s="
                f"{config.actor_restart_timeout_s:g}s "
                f"(incarnation {state.incarnation})")
        if len(state.queue) >= config.actor_restart_buffer_max:
            raise ActorUnavailableError(
                f"actor {state.actor_id.hex()[:12]} is RESTARTING and "
                f"its call buffer is full (actor_restart_buffer_max="
                f"{config.actor_restart_buffer_max})")

    def _actor_call_completed(self, spec: _TaskSpec):
        """Advance the actor's completed-call watermark: a replayed call
        at a seq the watermark already covers is served from the store
        by _dispatch_actor, never re-executed (exactly-once result
        delivery on top of at-least-once execution)."""
        if spec.actor_id is None or spec.seq is None:
            return
        state = self._actors.get(spec.actor_id)
        if state is None:
            return
        with self._lock:
            state.completed_seqs.add(spec.seq)
            while state.seq_watermark in state.completed_seqs:
                state.completed_seqs.discard(state.seq_watermark)
                state.seq_watermark += 1

    def _actor_retry_exceptions(self, spec: _TaskSpec):
        """Resolved retry_exceptions setting for one call: per-call
        option > per-method/class default > False. True retries any
        application exception; a list/tuple retries matching types."""
        copts = spec.options or {}
        if "retry_exceptions" in copts:
            return copts["retry_exceptions"]
        state = self._actors.get(spec.actor_id)
        return state.opts.get("retry_exceptions", False) if state else False

    def _maybe_retry_actor_error(self, spec: _TaskSpec, err_payload) -> bool:
        """Application-error retry (reference: retry_exceptions,
        task_manager.cc RetryTaskIfPossible): when the call's resolved
        retry_exceptions setting matches the raised error and retry
        budget remains, requeue it at the front of the actor's queue
        instead of delivering the error."""
        if (spec.actor_id is None or spec.stream is not None
                or spec.retries_left == 0):
            return False
        retry_on = self._actor_retry_exceptions(spec)
        if not retry_on:
            return False
        if retry_on is not True:
            try:
                v = protocol.deserialize_payload(err_payload,
                                                 store=self.store)
                err = v.error if isinstance(v, protocol.ErrorValue) else v
                cause = err.cause if isinstance(err, TaskError) else err
                if not isinstance(cause, tuple(retry_on)):
                    return False
            # rtpu-lint: disable=L4 — an error payload that cannot be
            # deserialized (or a malformed retry_exceptions list) cannot
            # be matched: deliver the original error instead of retrying
            except Exception:  # noqa: BLE001
                return False
        state = self._actors.get(spec.actor_id)
        if state is None or state.dead:
            return False
        if spec.retries_left > 0:
            spec.retries_left -= 1
        with self._lock:
            state.queue.appendleft(spec)
        self._dispatch_actor(state)
        return True

    def _adopt_sealed_actor_result(self, spec: _TaskSpec) -> bool:
        """Exactly-once result delivery for a call in flight at worker
        death: if the worker sealed every return container before dying
        (death landed between the seal and the DONE report flushing),
        adopt the results from the store instead of re-executing the
        call — its side effect already happened exactly once."""
        if spec.cancelled or spec.stream is not None:
            return False
        with self._lock:
            entries = [self._objects.get(rid) for rid in spec.return_ids]
        sealed = True
        for e in entries:
            if e is None or not e.event.is_set():
                sealed = False
                break
        if not sealed:
            try:
                if not all(self.store.contains(rid)
                           for rid in spec.return_ids):
                    return False
            # rtpu-lint: disable=L4 — a store probe that fails (store
            # closing, container racing an eviction) simply means the
            # result is NOT recoverable: fall back to replaying the call
            except Exception:  # noqa: BLE001
                return False
            for rid in spec.return_ids:
                # same descriptor the worker's DONE report would have
                # carried; _store_payload adopts the retained seal ref
                self._store_payload(rid, ("shm", rid.binary()))
        with self._lock:
            self._release_spec_locked(spec)
        self._release_spec_deps(spec)
        self._release_spec_args(spec)
        self._cancellable.pop(spec.return_ids[0].binary(), None)
        self._actor_call_completed(spec)
        return True

    def _actor_restart_deadline(self, state: _ActorState, incarnation: int):
        """actor_restart_timeout_s elapsed for one restart attempt: if
        that SAME restart is still in progress, fail the buffered calls
        with ActorUnavailableError. The restart itself keeps going — a
        later call may find the actor ALIVE again."""
        if self._shutdown:
            return
        with self._lock:
            stuck = (state.restarting and not state.dead
                     and state.incarnation == incarnation)
            buffered = list(state.queue) if stuck else []
            if stuck:
                state.queue.clear()
        if not buffered:
            return
        err = ActorUnavailableError(
            f"actor {state.actor_id.hex()[:12]} did not finish restarting "
            f"within actor_restart_timeout_s="
            f"{config.actor_restart_timeout_s:g}s "
            f"(incarnation {incarnation})")
        for spec in buffered:
            self._cancellable.pop(spec.return_ids[0].binary(), None)
            self._release_spec_args(spec)
            self._store_error(spec.return_ids, err)

    def _on_actor_error(self, w: _Worker, actor_id: ActorID, err_payload):
        state = self._actors.get(actor_id)
        if state is None:
            return
        try:
            v = protocol.deserialize_payload(err_payload, store=self.store)
            err = v.error if isinstance(v, protocol.ErrorValue) else v
        except Exception as e:  # noqa: BLE001
            err = ActorDiedError(f"actor constructor failed: {e}")
        self._mark_actor_dead(state, err)

    def _mark_actor_dead(self, state: _ActorState, cause: BaseException):
        with self._lock:
            if state.dead:
                return  # keep the original death cause
            state.dead = True
            state.ready = False
            state.restarting = False
            state.death_cause = cause
            if state.name and self._named_actors.get(state.name) == \
                    state.actor_id:
                # Terminal death frees the name: a later named create or
                # get-or-create (e.g. a collective coordinator re-formed
                # after a gang restart) must not rendezvous with this
                # corpse (reference: GCS removes the named-actor entry on
                # terminal death).
                del self._named_actors[state.name]
            pending = list(state.queue)
            state.queue.clear()
            self._release_actor_locked(state)
            try:
                self._pending_actors.remove(state)
            except ValueError:
                pass
        state.creation_event.set()
        if (state.restarts_left == 0
                and state.creation_args_payload is not None
                and state.creation_args_payload[0] == "shm"):
            # terminal death: the creation-args container is never needed
            # again — release the adopted ref and free it
            self._unpin_args(state.creation_args_payload[1])
        err = (cause if isinstance(cause, ActorDiedError)
               else self._actor_dead_error(state))
        self._publish_actor_state(state, "DEAD")
        for spec in pending:
            self._store_error(spec.return_ids, err)
        self._retry_pending_pgs()
        self._dispatch()

    def _handle_actor_worker_death(self, actor_id: ActorID):
        state = self._actors.get(actor_id)
        if state is None:
            return
        if state.restarts_left != 0 and not state.dead:
            if state.restarts_left > 0:
                state.restarts_left -= 1
            with self._lock:
                state.ready = False
                state.worker = None
                state.restarting = True
                state.restarting_since = time.monotonic()
                state.incarnation += 1
                incarnation = state.incarnation
            self._publish_actor_state(state, "RESTARTING")
            # bound the RESTARTING window: past the deadline the calls
            # buffered for this incarnation fail with
            # ActorUnavailableError (restarts are rare; one short-lived
            # timer thread per attempt is fine)
            timer = threading.Timer(
                config.actor_restart_timeout_s,
                self._actor_restart_deadline, args=(state, incarnation))
            timer.daemon = True
            timer.start()
            self._actor_start_queue.put(state)
        else:
            self._mark_actor_dead(
                state, ActorDiedError("the actor's worker process died")
            )

    def submit_actor_task(self, actor_id: ActorID, method: str, args: tuple,
                          kwargs: dict, num_returns=1,
                          options: Optional[dict] = None) -> List[ObjectRef]:
        state = self._actors.get(actor_id)
        if state is None:
            raise ActorDiedError(f"unknown actor {actor_id}")
        # RESTARTING admission: buffer, or raise ActorUnavailableError
        # past the buffer/deadline — before any state is built
        self._check_actor_admission(state)
        streaming = num_returns == "streaming"
        if streaming:
            num_returns = 1
        task_id = make_task_id(self.job_id)
        args2, kwargs2, deps = self._swap_top_level_refs(args, kwargs)
        args_payload, _ = protocol.serialize_args(args2, kwargs2, store=self.store)
        return_ids = [ObjectID.from_random() for _ in range(num_returns)]
        for rid in return_ids:
            self._entry(rid)
        if streaming:
            # registered before the dead-actor check so the error routes
            # through the stream (consumer gets a raising ref, then end)
            self._register_stream(return_ids[0].binary())
        if state.dead:
            refs = [ObjectRef(rid, core=self) for rid in return_ids]
            self._store_error(return_ids, self._actor_dead_error(state))
            return refs
        spec = _TaskSpec(task_id, None, args_payload, deps, return_ids,
                         dict(options or {}), actor_id=actor_id,
                         method=method)
        if streaming:
            spec.stream = self._stream_opts(return_ids[0].binary())
        self._cancellable[return_ids[0].binary()] = spec
        self._enqueue(spec)
        return [ObjectRef(rid, core=self) for rid in return_ids]

    def cancel_task(self, ref: ObjectRef, force: bool = False):
        """Best-effort task cancellation (reference: ray.cancel,
        python/ray/_private/worker.py:2970).

        A task still queued (or waiting on deps) is dropped and its caller
        sees TaskCancelledError at get(). A task already executing is
        interrupted with SIGINT (force=False, raising KeyboardInterrupt in
        the worker like the reference) or its worker is killed (force=True).
        Already-finished tasks are unaffected.
        """
        key = ref.id.binary()
        exec_worker = None
        removed = False
        inflight = False
        with self._lock:
            spec = self._cancellable.get(key)
            if spec is None:
                return
            spec.cancelled = True
            try:
                self._task_queue.remove(spec)
                removed = True
            except ValueError:
                pass
            if not removed and spec.actor_id is not None:
                state = self._actors.get(spec.actor_id)
                if state is not None:
                    try:
                        state.queue.remove(spec)
                        removed = True
                    except ValueError:
                        pass
            if not removed:
                tid = spec.task_id.binary()
                for w in self._workers.values():
                    if tid in w.inflight:
                        inflight = True
                        # Only signal when the target is the *executing*
                        # (head) entry — a SIGINT (or force-kill) for a task
                        # batched behind it would take out an innocent
                        # neighbour; batched targets are converted at
                        # completion instead (spec.cancelled check in
                        # _on_task_done).
                        if next(iter(w.inflight)) == tid:
                            exec_worker = w
                        break
        if removed or not inflight:
            # Queued, or still waiting on deps: it never acquired resources
            # and will never run — fail the caller immediately (the
            # reference also fails pending tasks at cancel time).
            self._store_error(spec.return_ids,
                              TaskCancelledError("task was cancelled"))
            self._dispatch()
        elif exec_worker is not None and exec_worker.proc is not None:
            import signal

            try:
                if force:
                    exec_worker.proc.terminate()
                else:
                    os.kill(exec_worker.proc.pid, signal.SIGINT)
            except OSError:
                pass

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        state = self._actors.get(actor_id)
        if state is None:
            return
        if no_restart:
            state.restarts_left = 0
        with self._lock:
            w = state.worker
        if not no_restart and state.restarts_left != 0 and not state.dead:
            # kill(no_restart=False) with restart budget left behaves
            # exactly like a worker death: the budget is consumed and
            # the actor restarts; queued + in-flight calls follow the
            # normal replay path (reference: ray.kill(no_restart=False)
            # routes through the GCS restart FSM, gcs_actor_manager.cc).
            if w is not None and w.proc is not None:
                try:
                    w.proc.kill()
                except OSError:
                    pass
                # reader-thread EOF -> _on_worker_death -> replay +
                # _handle_actor_worker_death consumes the budget
            # no live worker: the actor is starting or already mid-
            # restart — there is no incarnation to kill
            return
        self._mark_actor_dead(state, ActorDiedError("actor was killed via kill()"))
        if w is not None and w.proc is not None:
            # ray.kill semantics are FORCEFUL (no exit handlers), so
            # escalate to SIGKILL — SIGTERM alone is not a kill for
            # processes that trap it (train workers route SIGTERM to the
            # preemption flag, and a worker blocked in a cross-process
            # collective never reaches a python signal handler at all)
            try:
                w.proc.terminate()
                w.proc.kill()
            except OSError:
                pass

    def evict_actor(self, actor_id: ActorID, wait_s: float = 0.5) -> bool:
        """Planned-migration eviction (node drain): remove the local
        incarnation only once its queued and in-flight calls have
        settled — unlike kill_actor, nothing pending is failed and no
        DEAD state is published (the drain migrator already published
        RESTARTING and recreates the actor elsewhere). Returns False
        while calls are still settling, so the caller can keep polling
        inside the drain grace window."""
        state = self._actors.get(actor_id)
        if state is None or state.dead:
            return True
        deadline = time.monotonic() + wait_s
        while True:
            with self._lock:
                w = state.worker
                busy = len(state.queue) + (
                    len(w.inflight) if w is not None else 0)
                if not busy:
                    # settle-and-mark under one hold: a call racing in
                    # after this point fails at submit admission, where
                    # the driver's actor_state retry path re-routes it
                    # to the new incarnation
                    state.dead = True
                    state.migrated = True
                    state.ready = False
                    state.restarting = False
                    state.death_cause = ActorDiedError(
                        "actor migrated off a draining node")
                    if state.name and self._named_actors.get(
                            state.name) == state.actor_id:
                        del self._named_actors[state.name]
                    self._release_actor_locked(state)
                    try:
                        self._pending_actors.remove(state)
                    except ValueError:
                        pass
                    break
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)
        state.creation_event.set()
        if w is not None and w.proc is not None:
            try:
                w.proc.terminate()
                w.proc.kill()
            except OSError:
                pass
        self._dispatch()
        return True

    def get_actor_method_opts(self, actor_id: ActorID) -> dict:
        state = self._actors.get(actor_id)
        return state.opts.get("method_opts", {}) if state else {}

    def get_named_actor(self, name: str) -> ActorID:
        with self._lock:
            aid = self._named_actors.get(name)
        if aid is None:
            raise ValueError(f"no actor named {name!r}")
        return aid

    # ------------------------------------------------- placement groups

    def create_placement_group(self, bundles, strategy, name) -> PlacementGroup:
        pg_id = PlacementGroupID.from_random()
        state = PlacementGroupState(pg_id, bundles, strategy, name)
        for b in state.bundles:
            if not b.reserved.is_subset_of(self._total):
                raise ValueError(
                    f"bundle {b.spec} can never fit this node's resources "
                    f"{self._total.to_dict()}"
                )
        if strategy == "STRICT_SPREAD" and len(bundles) > 1:
            state.infeasible_reason = (
                "STRICT_SPREAD requires one node per bundle; the single-node "
                "runtime cannot satisfy it"
            )
        reserved = False
        with self._lock:
            self._pgs[pg_id] = state
            if state.infeasible_reason is None:
                reserved = self._try_reserve_pg_locked(state)
                if not reserved:
                    self._pending_pgs.append(state)
        if reserved:
            self._resolve_pg_waiters(state)
        return PlacementGroup(pg_id, bundles)

    def _try_reserve_pg_locked(self, state: PlacementGroupState) -> bool:
        if state.infeasible_reason or state.removed:
            return False
        total = state.total_request()
        if not total.is_subset_of(self._avail):
            return False
        n_total = int(total.get("TPU"))
        if n_total:
            if self.topology is None:
                return False
            if state.strategy == "STRICT_PACK":
                # one ICI-contiguous rectangle for the whole gang
                chips = self.topology.allocate(n_total, contiguous=True)
                if chips is None:
                    return False
                off = 0
                for b in state.bundles:
                    n = int(b.reserved.get("TPU"))
                    b.chips = chips[off:off + n]
                    b.free_chips = list(b.chips)
                    off += n
            else:
                contig = state.strategy == "PACK"
                allocs = []
                ok = True
                for b in state.bundles:
                    n = int(b.reserved.get("TPU"))
                    if not n:
                        continue
                    got = self.topology.allocate(n, contiguous=contig)
                    if got is None and contig:
                        got = self.topology.allocate(n, contiguous=False)
                    if got is None:
                        ok = False
                        break
                    allocs.append((b, got))
                if not ok:
                    for _, g in allocs:
                        self.topology.release(g)
                    return False
                for b, g in allocs:
                    b.chips = g
                    b.free_chips = list(g)
        self._avail = self._avail - total
        state.ready_event.set()
        return True

    def _resolve_pg_waiters(self, state: PlacementGroupState):
        with self._lock:
            waiters = self._pg_ready_waiters.pop(state.id, [])
        payload = protocol.serialize_value(True, store=None)
        for oid in waiters:
            self._store_payload(oid, payload)

    def placement_group_ready_ref(self, pg_id: PlacementGroupID) -> ObjectRef:
        oid = ObjectID.from_random()
        self._entry(oid)
        resolve_now = False
        err = None
        with self._lock:
            state = self._pgs.get(pg_id)
            if state is None:
                err = PlacementGroupError(f"unknown placement group {pg_id}")
            elif state.removed:
                err = PlacementGroupError("placement group was removed")
            elif state.infeasible_reason:
                err = PlacementGroupError(state.infeasible_reason)
            elif state.ready_event.is_set():
                resolve_now = True
            else:
                self._pg_ready_waiters.setdefault(pg_id, []).append(oid)
        if err is not None:
            self._store_error([oid], err)
        elif resolve_now:
            self._store_payload(oid, protocol.serialize_value(True, store=None))
        return ObjectRef(oid, core=self)

    def wait_placement_group(self, pg_id: PlacementGroupID,
                             timeout: float) -> bool:
        with self._lock:
            state = self._pgs.get(pg_id)
        if state is None:
            raise PlacementGroupError(f"unknown placement group {pg_id}")
        return state.ready_event.wait(timeout)

    def placement_group_chips(self, pg_id: PlacementGroupID,
                              index: int) -> List[int]:
        with self._lock:
            state = self._pgs.get(pg_id)
        if state is None:
            raise PlacementGroupError(f"unknown placement group {pg_id}")
        return list(state.bundles[index].chips)

    def remove_placement_group(self, pg_id: PlacementGroupID):
        with self._lock:
            state = self._pgs.get(pg_id)
            if state is None or state.removed:
                return
            state.removed = True
            try:
                self._pending_pgs.remove(state)
            except ValueError:
                pass
            if state.ready_event.is_set():
                for b in state.bundles:
                    unconsumed = b.reserved.subtract_unchecked(b.consumed)
                    self._avail = self._avail + unconsumed
                    if self.topology is not None and b.free_chips:
                        self.topology.release(b.free_chips)
                        b.free_chips = []
            waiters = self._pg_ready_waiters.pop(pg_id, [])
            orphaned = [s for s in self._task_queue
                        if s.pg_wire is not None and s.pg_wire[1] == pg_id.binary()]
            for s in orphaned:
                self._task_queue.remove(s)
            orphaned_actors = [
                a for a in self._pending_actors
                if a.pg_wire is not None and a.pg_wire[1] == pg_id.binary()
            ]
        err = PlacementGroupError("placement group was removed")
        if waiters:
            self._store_error(waiters, err)
        for s in orphaned:
            self._store_error(s.return_ids, err)
        for a in orphaned_actors:
            self._mark_actor_dead(a, ActorDiedError(
                "placement group was removed before the actor was placed"))
        self._retry_pending_pgs()
        self._dispatch()

    def placement_group_table(self) -> Dict[str, dict]:
        out = {}
        with self._lock:
            for pg_id, state in self._pgs.items():
                out[pg_id.hex()] = {
                    "name": state.name,
                    "strategy": state.strategy,
                    "bundles": [b.spec for b in state.bundles],
                    "chips": [b.chips for b in state.bundles],
                    "state": ("REMOVED" if state.removed else
                              "CREATED" if state.ready_event.is_set() else
                              "PENDING"),
                    "infeasible_reason": state.infeasible_reason,
                }
        return out

    def _retry_pending_pgs(self):
        newly_ready = []
        to_start = []
        with self._lock:
            still = []
            for st in self._pending_pgs:
                if self._try_reserve_pg_locked(st):
                    newly_ready.append(st)
                else:
                    still.append(st)
            self._pending_pgs = still
            still_a = []
            for astate in self._pending_actors:
                if astate.dead:
                    continue
                if self._try_acquire_actor_locked(astate):
                    to_start.append(astate)
                else:
                    still_a.append(astate)
            self._pending_actors = still_a
        for st in newly_ready:
            self._resolve_pg_waiters(st)
        for astate in to_start:
            self._actor_start_queue.put(astate)
        if newly_ready:
            self._dispatch()

    def _try_acquire_actor_locked(self, state: _ActorState) -> bool:
        """Acquire an actor's resources (+ concrete chips). Holds _lock."""
        req = state.request
        n_tpus = int(req.get("TPU")) if req is not None else 0
        if state.pg_wire is not None:
            pg = self._pgs.get(PlacementGroupID(state.pg_wire[1]))
            if pg is None or pg.removed or not pg.ready_event.is_set():
                return False
            bundle = pg.find_bundle(req or ResourceSet(), state.pg_wire[2])
            if bundle is None:
                return False
            if n_tpus and len(bundle.free_chips) < n_tpus:
                return False
            bundle.acquire(req or ResourceSet())
            state.acquired_bundle = bundle
            state.chips = bundle.take_chips(n_tpus) if n_tpus else []
            state.resources_acquired = True
            return True
        if req is not None and not req.is_subset_of(self._avail):
            return False
        chips: List[int] = []
        if n_tpus:
            if self.topology is None:
                return False
            got = self.topology.allocate(n_tpus, contiguous=True)
            if got is None:
                got = self.topology.allocate(n_tpus, contiguous=False)
            if got is None:
                return False
            chips = got
        if req is not None:
            self._avail = self._avail - req
        state.chips = chips
        state.resources_acquired = True
        return True

    def _release_actor_locked(self, state: _ActorState):
        req = state.request
        if req is None or not state.resources_acquired:
            return  # never acquired (still pending) -> nothing to credit
        state.resources_acquired = False
        if state.acquired_bundle is not None:
            state.acquired_bundle.release(req)
            pg_removed = False
            if state.pg_wire is not None:
                pg = self._pgs.get(PlacementGroupID(state.pg_wire[1]))
                pg_removed = pg is None or pg.removed
            if pg_removed:
                if self.topology is not None and state.chips:
                    self.topology.release(state.chips)
            else:
                state.acquired_bundle.return_chips(state.chips)
            state.acquired_bundle = None
        else:
            self._avail = self._avail + req
            if self.topology is not None and state.chips:
                self.topology.release(state.chips)
        state.request = None
        state.chips = []

    # ------------------------------------------------------------ data server

    def _apply_worker_submit(self, fn_id, pickled_fn, args_payload,
                             return_ids: List[ObjectID], options: dict):
        """Shared body of REQ_SUBMIT (server-generated ids) and
        REQ_SUBMIT_ASYNC (worker-generated ids, no reply)."""
        if pickled_fn is not None:
            with self._lock:
                self._functions.setdefault(fn_id, pickled_fn)
        options = dict(options)
        deps = options.pop("__deps", [])
        nested = options.pop("__nested", [])
        parent = options.pop("__parent", None)
        streaming = options.pop("__stream", False)
        task_id = make_task_id(self.job_id)
        for rid in return_ids:
            self._entry(rid)
        spec = _TaskSpec(task_id, fn_id, args_payload,
                         [ObjectID(d) for d in deps], return_ids, options)
        spec.parent_task = parent
        spec.nested_deps = [ObjectID(b) for b in nested]
        spec.request, spec.pg_wire = self._prepare_request(
            options, is_actor=False)
        self._cancellable[return_ids[0].binary()] = spec
        if streaming:
            seed = return_ids[0].binary()
            spec.stream = self._stream_opts(seed)
            self._register_stream(seed)
        else:
            self._record_lineage(spec)
        self._enqueue(spec)

    def _apply_worker_actor_call(self, actor_id_b, method, args_payload,
                                 extra: dict, return_ids: List[ObjectID]):
        """Shared body of REQ_ACTOR_CALL / REQ_ACTOR_CALL_ASYNC."""
        state = self._actors.get(ActorID(actor_id_b))
        if state is None:
            raise ActorDiedError("unknown actor")
        deps = [ObjectID(d) for d in extra.get("__deps", [])]
        task_id = make_task_id(self.job_id)
        for rid in return_ids:
            self._entry(rid)
        spec = _TaskSpec(task_id, None, args_payload, deps, return_ids,
                         dict(extra.get("__opts") or {}),
                         actor_id=state.actor_id, method=method)
        spec.parent_task = extra.get("__parent")
        if extra.get("__stream"):
            seed = return_ids[0].binary()
            spec.stream = self._stream_opts(seed)
            self._register_stream(seed)
        if state.dead:
            self._store_error(return_ids, self._actor_dead_error(state))
        else:
            # raises ActorUnavailableError past the RESTARTING buffer;
            # the data-server handlers preserve ActorError subtypes
            self._check_actor_admission(state)
            self._enqueue(spec)

    def _data_server(self, w: _Worker):
        conn = w.data_conn
        try:
            while True:
                msg = conn.recv()
                try:
                    reply = self._handle_data_request(w, msg)
                except BaseException as e:  # noqa: BLE001
                    # Preserve the exception type (GetTimeoutError,
                    # ActorDiedError, ...) so worker-side handlers behave
                    # exactly like driver-side ones. Errors in a
                    # fire-and-forget request have no reply channel —
                    # they were already stored into the return entries
                    # (or are put-metadata failures, surfaced at get).
                    if msg and str(msg[0]).endswith("_async"):
                        continue
                    reply = ("err", protocol.serialize_value(
                        protocol.ErrorValue(e), store=None))
                if reply is not protocol.NO_REPLY:
                    conn.send(reply)
        except (EOFError, OSError):
            pass

    def register_package(self, pkg_hash: str, data: bytes) -> None:
        """Store a runtime_env package (driver-side prepare)."""
        self._packages[pkg_hash] = data

    def _get_package(self, pkg_hash: str):
        return self._packages.get(pkg_hash)

    def prepare_runtime_env(self, runtime_env):
        from ray_tpu.core import runtime_env as _re

        return _re.prepare(self, runtime_env)

    def _handle_data_request(self, w: _Worker, msg):
        tag = msg[0]
        if tag == protocol.REQ_GET:
            _, oid_bytes_list, timeout_ms, cur_task = msg
            timeout = None if timeout_ms < 0 else timeout_ms / 1000.0
            deadline = None if timeout is None else time.monotonic() + timeout
            payloads = {}
            entries = [self._entry(ObjectID(b)) for b in oid_bytes_list]
            if not all(e.event.is_set() for e in entries):
                self._mark_worker_blocked(w, cur_task)
            try:
                for b, e in zip(oid_bytes_list, entries):
                    while True:
                        remaining = None if deadline is None else max(
                            0.0, deadline - time.monotonic())
                        if not e.event.wait(remaining):
                            raise GetTimeoutError(
                                "get() timed out in worker request")
                        payload = e.payload
                        if payload is None:
                            # reset mid-reconstruction: wait for the
                            # recomputed value
                            continue
                        if self._payload_lost(payload):
                            if self._recover_object(b):
                                continue
                            # unrecoverable: ship the enriched error so
                            # the worker's read raises it
                            payload = protocol.serialize_value(
                                protocol.ErrorValue(self._lost_error(b)),
                                store=None)
                        payloads[b] = payload
                        break
            finally:
                self._unmark_worker_blocked(w, cur_task)
            return ("ok", payloads)
        if tag == protocol.REQ_NEED_SPACE:
            return ("ok", self._try_free_space(msg[1]))
        if tag == protocol.REQ_FREE:
            return ("ok", self.free_objects(msg[1]))
        if tag == protocol.REQ_KILL_ACTOR:
            self.kill_actor(ActorID(msg[1]), no_restart=msg[2])
            return ("ok",)
        if tag == protocol.REQ_PUT_META:
            _, oid_bytes, payload = msg
            oid = ObjectID(oid_bytes)
            self._store_payload(oid, ("shm", oid_bytes) if payload is None else payload)
            return ("ok",)
        if tag == protocol.REQ_PUT_META_ASYNC:
            _, oid_bytes, payload = msg
            oid = ObjectID(oid_bytes)
            try:
                self._store_payload(
                    oid, ("shm", oid_bytes) if payload is None else payload)
            except BaseException as e:  # noqa: BLE001 — no reply channel:
                # the worker already holds the ref, so the error must
                # live in the entry or a later get() hangs forever
                self._store_error(
                    [oid], TaskError(f"put failed owner-side: {e!r}"))
            return protocol.NO_REPLY
        if tag == protocol.REQ_BARRIER:
            # sync point: all earlier fire-and-forget sends on this conn
            # are applied once this replies (FIFO per connection)
            return ("ok",)
        if tag == protocol.REQ_STREAM_NEXT:
            # one bounded wait slice (the worker loops on "pending", so a
            # cancel SIGINT never lands mid-recv of an unbounded request)
            _, seed, index, timeout_ms, owner = msg
            st = self._streams.get(seed)
            if st is None:
                raise ValueError(f"unknown stream {seed.hex()}")
            with st.cond:
                hit = self._stream_poll_locked(st, index)
            if hit is not None:
                return hit
            deadline = time.monotonic() + timeout_ms / 1000.0
            self._mark_worker_blocked(w, None)
            try:
                with st.cond:
                    while True:
                        hit = self._stream_poll_locked(st, index)
                        if hit is not None:
                            return hit
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return ("pending",)
                        st.cond.wait(remaining)
            finally:
                self._unmark_worker_blocked(w, None)
        if tag == protocol.REQ_STREAM_CREDIT:
            _, seed, produced = msg
            st = self._streams.get(seed)
            if st is None:
                # stream reaped/unknown: report full consumption so a
                # producer can never block on a dead stream
                return ("ok", produced)
            with st.cond:
                return ("ok", st.consumed)
        if tag == protocol.REQ_STREAM_CONSUMED_ASYNC:
            _, seed, index, owner = msg
            self.stream_consumed(seed, index)
            return protocol.NO_REPLY
        if tag == protocol.REQ_SUBMIT_ASYNC:
            # worker pre-generated the return ids: apply without replying
            _, fn_id, pickled_fn, args_payload, inline_values, \
                return_ids_b, options = msg
            return_ids = [ObjectID(b) for b in return_ids_b]
            try:
                self._apply_worker_submit(fn_id, pickled_fn, args_payload,
                                          return_ids, options)
            except BaseException as e:  # noqa: BLE001 — surface at get()
                self._store_error(
                    return_ids, e if isinstance(e, TaskError)
                    else TaskError(f"submission failed: {e!r}"))
            return protocol.NO_REPLY
        if tag == protocol.REQ_ACTOR_CALL_ASYNC:
            _, actor_id_b, method, args_payload, extra, return_ids_b = msg
            return_ids = [ObjectID(b) for b in return_ids_b]
            try:
                self._apply_worker_actor_call(actor_id_b, method,
                                              args_payload, extra,
                                              return_ids)
            except BaseException as e:  # noqa: BLE001 — surface at get()
                from ray_tpu.exceptions import ActorError

                # _store_error creates missing entries itself; ActorError
                # subtypes (ActorDiedError, ActorUnavailableError) must
                # reach the caller as-is
                self._store_error(
                    return_ids, e if isinstance(e, ActorError)
                    else ActorDiedError(f"actor call failed: {e!r}"))
            return protocol.NO_REPLY
        if tag == protocol.REQ_SUBMIT:
            _, fn_id, pickled_fn, args_payload, inline_values, n_returns, options = msg
            return_ids = [ObjectID.from_random() for _ in range(n_returns)]
            self._apply_worker_submit(fn_id, pickled_fn, args_payload,
                                      return_ids, options)
            return ("ok", [r.binary() for r in return_ids])
        if tag == protocol.REQ_ACTOR_CALL:
            _, actor_id_b, method, args_payload, extra, n_returns = msg
            return_ids = [ObjectID.from_random() for _ in range(n_returns)]
            self._apply_worker_actor_call(actor_id_b, method, args_payload,
                                          extra, return_ids)
            return ("ok", [r.binary() for r in return_ids])
        if tag == protocol.REQ_WAIT:
            _, oid_bytes_list, num_returns, timeout_s, cur_task = msg
            refs = [ObjectRef(ObjectID(b), core=self) for b in oid_bytes_list]
            self._mark_worker_blocked(w, cur_task)
            try:
                ready, rest = self.wait(refs, num_returns=num_returns,
                                        timeout=timeout_s)
            finally:
                self._unmark_worker_blocked(w, cur_task)
            return ("ok", [x.binary() for x in ready], [x.binary() for x in rest])
        if tag == protocol.REQ_PKG:
            return ("ok", self._get_package(msg[1]))
        if tag == protocol.REQ_PKG_PUT:
            self.register_package(msg[1], msg[2])
            return ("ok", None)
        if tag == protocol.REQ_KV:
            _, op, key, value = msg
            if op == "get":
                return ("ok", self._kv.get(key))
            if op == "put":
                self._kv[key] = value
                return ("ok", None)
            if op == "del":
                self._kv.pop(key, None)
                return ("ok", None)
            raise ValueError(f"bad kv op {op}")
        if tag == protocol.REQ_PUBSUB:
            _, op, channel, arg, timeout = msg
            return ("ok", self.pubsub_op(op, channel, arg, timeout))
        if tag == protocol.REQ_PG:
            _, op, *args = msg
            if op == "create":
                bundles, strategy, name = args
                pg = self.create_placement_group(bundles, strategy, name)
                return ("ok", (pg.id.binary(), pg.bundle_specs))
            if op == "remove":
                self.remove_placement_group(PlacementGroupID(args[0]))
                return ("ok", None)
            if op == "ready_ref":
                ref = self.placement_group_ready_ref(PlacementGroupID(args[0]))
                return ("ok", ref.binary())
            if op == "wait":
                return ("ok", self.wait_placement_group(
                    PlacementGroupID(args[0]), args[1]))
            if op == "chips":
                return ("ok", self.placement_group_chips(
                    PlacementGroupID(args[0]), args[1]))
            if op == "table":
                return ("ok", self.placement_group_table())
            raise ValueError(f"unknown pg op {op!r}")
        if tag == protocol.REQ_CREATE_ACTOR:
            _, fn_id, pickled_cls, args_payload, deps, opts = msg
            if pickled_cls is not None:
                with self._lock:
                    self._functions.setdefault(fn_id, pickled_cls)
            actor_id = self._create_actor_from_payload(
                fn_id, args_payload, [ObjectID(d) for d in deps], opts or {})
            return ("ok", actor_id.binary())
        if tag == protocol.REQ_CANCEL:
            _, oid_bytes, force = msg
            self.cancel_task(ObjectRef(ObjectID(oid_bytes), core=self),
                             force=force)
            return ("ok", None)
        if tag == protocol.REQ_GET_ACTOR:
            _, name = msg
            aid = self.get_named_actor(name)
            from ray_tpu.core.actor import ActorHandle

            handle = ActorHandle(aid, self.get_actor_method_opts(aid))
            return ("ok", protocol.serialize_value(handle, store=None))
        raise ValueError(f"unknown data request {tag!r}")

    # -------------------------------------------------------------- lifecycle

    def stack_dump(self, timeout_s: float = 2.0) -> Dict[str, str]:
        """Live profile of every worker: SIGUSR1 triggers each worker's
        stack-dump handler, then the dump files are collected
        (reference role: the dashboard's py-spy stack endpoint). Returns
        {worker_id_hex: stacks_text}."""
        import signal as _signal

        from ray_tpu.core.proc_stats import stack_dump_path

        with self._lock:
            targets = [(w.worker_id.hex(), w.proc.pid)
                       for w in self._workers.values()
                       if w.alive and w.proc is not None]
        paths = {}
        for wid, pid in targets:
            path = stack_dump_path(pid)
            try:
                os.unlink(path)
            except OSError:
                pass
            try:
                os.kill(pid, _signal.SIGUSR1)
                paths[wid] = path
            except OSError:
                continue
        out: Dict[str, str] = {}
        deadline = time.monotonic() + timeout_s
        while paths and time.monotonic() < deadline:
            for wid, path in list(paths.items()):
                try:
                    with open(path) as f:
                        out[wid] = f.read()
                    paths.pop(wid)
                    os.unlink(path)
                except OSError:
                    continue
            if paths:
                time.sleep(0.02)
        for wid in paths:
            out[wid] = "<no dump: worker busy in non-python code>"
        return out

    def state_summary(self) -> dict:
        """Introspection snapshot for the state API (reference:
        python/ray/util/state/api.py:781 backed by the GCS/raylet state
        services; here the runtime answers directly)."""
        from ray_tpu.core.proc_stats import CpuTracker

        with self._lock:
            if not hasattr(self, "_cpu_tracker"):
                self._cpu_tracker = CpuTracker()
            self._cpu_tracker.prune(
                w.proc.pid for w in self._workers.values()
                if w.proc is not None)
            workers = []
            for w in self._workers.values():
                pid = w.proc.pid if w.proc else None
                entry = {
                    "worker_id": w.worker_id.hex(),
                    "pid": pid,
                    "alive": w.alive,
                    "actor_id": w.actor_id.hex() if w.actor_id else None,
                    "inflight": len(w.inflight),
                    "blocked": w.blocked,
                }
                # per-process CPU/RSS from /proc (reference:
                # reporter_agent.py:428 via psutil)
                if pid is not None and w.alive:
                    ps = self._cpu_tracker.stats(pid)
                    if ps is not None:
                        entry.update(ps)
                workers.append(entry)
            actors = [{
                "actor_id": s.actor_id.hex(),
                "name": s.name,
                "state": ("DEAD" if s.dead else
                          "RESTARTING" if s.restarting else
                          "ALIVE" if s.ready else "PENDING"),
                "restarts_left": s.restarts_left,
                "incarnation": s.incarnation,
                "queued_calls": len(s.queue),
            } for s in self._actors.values()]
            queued = len(self._task_queue)
            running = sum(len(w.inflight) for w in self._workers.values())
            objects = len(self._objects)
            resolved = sum(1 for e in self._objects.values()
                           if e.event.is_set())
            resources = {"total": self._total.to_dict(),
                         "available": self._avail.to_dict()}
            n_pgs = len(self._pgs)
        with self._spill_lock:
            pinned = len(self._pinned)
            spilled_bytes = self._spilled_bytes
        return {
            "node_id": self.node_id.hex(),
            "workers": workers,
            "actors": actors,
            "tasks": {"queued": queued, "running": running},
            "objects": {"tracked": objects, "resolved": resolved,
                        "pinned": pinned, "spilled_bytes": spilled_bytes},
            "resources": resources,
            "store": self.store.stats(),
            "placement_groups": n_pgs,
        }

    def kv_op(self, op: str, key: str, value=None):
        if op == "get":
            return self._kv.get(key)
        if op == "put":
            self._kv[key] = value
            return None
        if op == "del":
            self._kv.pop(key, None)
            return None
        raise ValueError(op)

    _CHANNEL_CAP = 10_000

    def pubsub_op(self, op: str, channel: str, arg=None,
                  timeout: float = 0.0):
        """Single-node mirror of the GCS pubsub plane (gcs.py
        _op_publish/_op_poll): ``publish`` appends to a bounded
        per-channel log and returns the seq; ``poll`` long-polls for
        messages with seq > arg, returning [(seq, message)]. Seqs are
        contiguous per channel so a slow subscriber can detect trimming.
        In cluster mode the overriding cores route these to the GCS."""
        if op == "publish":
            with self._pubsub_cond:
                seq = self._channel_seq.get(channel, 0) + 1
                self._channel_seq[channel] = seq
                log = self._channels.setdefault(channel, [])
                log.append((seq, arg))
                if len(log) > self._CHANNEL_CAP:
                    del log[: len(log) - self._CHANNEL_CAP]
                self._pubsub_cond.notify_all()
                return seq
        if op == "poll":
            since_seq = int(arg or 0)
            deadline = time.monotonic() + timeout
            with self._pubsub_cond:
                while True:
                    if self._channel_seq.get(channel, 0) > since_seq:
                        log = self._channels[channel]
                        first_seq = log[0][0]
                        start = max(0, since_seq + 1 - first_seq)
                        return log[start:]
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return []
                    self._pubsub_cond.wait(remaining)
        raise ValueError(op)

    # -------------------------------------------------- memory monitor

    def _memory_monitor_loop(self):
        """Poll memory usage; above the threshold, kill one worker per
        tick by the group-by-owner policy so the node sheds load instead
        of letting the kernel OOM-kill it wholesale."""
        from ray_tpu.core.memory_monitor import MemoryMonitor

        mon = MemoryMonitor(limit_bytes=config.memory_limit_bytes)
        while not self._shutdown:
            time.sleep(config.memory_monitor_interval_s)
            try:
                mon.limit_bytes = config.memory_limit_bytes  # reloadable
                with self._lock:
                    pids = [w.proc.pid for w in self._workers.values()
                            if w.alive and w.proc is not None]
                if mon.usage_fraction(pids) >= config.memory_usage_threshold:
                    self._kill_for_memory()
            # rtpu-lint: disable=L4 — crash-proof daemon loop: losing
            # the monitor silently disables OOM protection for the rest
            # of the session; one bad poll just skips a tick
            except Exception:  # noqa: BLE001 — monitoring must not die
                pass

    def _kill_for_memory(self):
        """Pick and SIGKILL one victim worker (reference policy,
        worker_killing_policy_group_by_owner.h): group running tasks by
        owner (submitting parent), prefer the group with the most
        in-flight tasks, and within it the NEWEST dispatch — last-in
        first-killed keeps earlier (likely further-along) work alive.
        Retriable tasks are preferred over non-retriable; actor workers
        are a last resort (their death is more disruptive)."""
        with self._lock:
            task_workers = []   # (group_size, dispatched_ts, worker)
            groups: Dict[Optional[str], int] = {}
            for w in self._workers.values():
                if not w.alive or w.actor_id is not None or not w.inflight:
                    continue
                head = next(iter(w.inflight.values()))
                groups[head.parent_task] = groups.get(head.parent_task,
                                                      0) + 1
            for w in self._workers.values():
                if not w.alive or w.actor_id is not None or not w.inflight:
                    continue
                head = next(iter(w.inflight.values()))
                retriable = (config.task_oom_retries < 0
                             or head.oom_kills < config.task_oom_retries)
                task_workers.append((
                    0 if retriable else 1,       # retriable first
                    -groups.get(head.parent_task, 0),  # biggest group
                    -head.dispatched_ts,         # newest dispatch
                    id(w), w))
            victim = None
            if task_workers:
                task_workers.sort(key=lambda t: t[:4])
                victim = task_workers[0][4]
            else:
                # no plain-task candidates: newest busy actor worker
                actors = [w for w in self._workers.values()
                          if w.alive and w.actor_id is not None
                          and w.inflight]
                if actors:
                    victim = actors[-1]
            if victim is None:
                return
            victim.oom_killed = True
            self._oom_kill_count += 1
        # kill the DESCENDANTS first: bounded-mode accounting charges the
        # worker's whole tree, so forked helpers (mp pools, loaders) must
        # die with it or their RSS survives the kill and the monitor
        # starts executing innocent workers
        try:
            from ray_tpu.core.memory_monitor import _descendants

            pid = victim.proc.pid
            for child in _descendants([pid]):
                if child != pid:
                    try:
                        os.kill(child, signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        pass
            victim.proc.kill()
        # rtpu-lint: disable=L4 — the victim (or its /proc entries) may
        # vanish mid-walk; an incomplete kill pass must not take the
        # memory monitor down with it
        except Exception:  # noqa: BLE001
            pass

    def prestart_workers(self, num: int):
        """Pre-spawn up to ``num`` EXTRA idle workers ahead of an
        anticipated burst (reference: WorkerPool::PrestartWorkers,
        src/ray/raylet/worker_pool.h:344 — there driven by task-backlog
        hints). With the zygote this is ~10ms each; surplus workers are
        retired by the normal pool-trim path once load passes."""
        with self._lock:
            if self._shutdown:
                return
            have = sum(1 for w in self._workers.values()
                       if w.alive and w.actor_id is None) + self._spawning
            want = min(num, 4 * self.num_workers - have)
        for _ in range(max(0, want)):
            self._spawn_worker()

    def wait_for_workers(self, count: Optional[int] = None,
                         timeout: Optional[float] = None):
        from ray_tpu.core.config import config

        if timeout is None:
            timeout = config.worker_register_timeout_s
        count = count or self.num_workers
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                n = sum(1 for w in self._workers.values() if w.ready)
            if n >= count:
                return
            time.sleep(0.005)
        raise TimeoutError(f"only some workers became ready within {timeout}s")

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            try:
                if w.task_conn is not None:
                    self._send_msg(w, (protocol.MSG_SHUTDOWN,))
            except (OSError, EOFError, BrokenPipeError):
                pass
        from ray_tpu.core.config import config

        deadline = time.monotonic() + config.worker_shutdown_grace_s
        for w in workers:
            try:
                w.proc.wait(timeout=max(0.05, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                w.proc.kill()
        with self._zygote_lock:
            # claim the zygote under its lock: a concurrent respawn can
            # drop/replace it (_fork_from_zygote nulls a wedged zygote),
            # so an unlocked check-then-terminate races to AttributeError
            zygote, self._zygote = self._zygote, None
        if zygote is not None:
            try:
                zygote.stdin.close()  # EOF -> zygote exits
                zygote.terminate()
            except (OSError, ValueError):
                pass  # pipe already broken / zygote already gone
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            os.unlink(self._sock_path)
        except OSError:
            pass
        self.store.close()
        if self._log_monitor is not None:
            self._log_monitor.stop(flush=True)  # drain final worker output
        import shutil

        external_storage.cleanup_dir(self._spill_dir)
        shutil.rmtree(os.path.join("/tmp", self._session),
                      ignore_errors=True)
        if runtime_context.get_core_or_none() is self:
            runtime_context.set_core(None)
